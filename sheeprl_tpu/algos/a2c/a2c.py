"""A2C training loop — TPU-native re-design of
/root/reference/sheeprl/algos/a2c/a2c.py:28-440.

The reference takes ONE optimizer step per iteration, accumulating gradients
over minibatches with ``no_backward_sync`` and calling backward only at the
end (a2c.py:60-96).  Accumulated minibatch gradients with sum/mean reduction
are mathematically the whole-batch gradient, so here the update is a single
jitted step over the full local rollout — one XLA graph, batched MXU matmuls,
``pmean`` across the mesh replacing the DDP all-reduce.
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.a2c.agent import build_agent
from sheeprl_tpu.algos.a2c.loss import policy_loss, value_loss
from sheeprl_tpu.algos.a2c.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.envs.player import fetch_values, obs_sharding
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.ops.numerics import gae
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import get_diagnostics, save_configs


def make_train_step(agent, optimizer, cfg, mesh):
    """One whole-batch gradient step, data-parallel over the mesh.

    Returns metrics ``[pg_loss, v_loss, grad_norm, nonfinite_steps]``; under
    ``diagnostics.sentinel.policy=skip_update`` a non-finite update is
    discarded in-graph (params/opt state keep their pre-step values).  With
    ``diagnostics.health`` on, a learn-health stats dict (grad/update/param
    norms, update/weight ratio, dead-unit fraction, value EV) rides the same
    output fetch; the global grad norm is computed once there and shared
    with the sentinel's finiteness check.
    """
    from sheeprl_tpu.diagnostics.health import explained_variance, health_spec, health_stats
    from sheeprl_tpu.diagnostics.sentinel import finite_flag, select_finite, sentinel_spec

    sentinel = sentinel_spec(cfg)
    health = health_spec(cfg)
    world = mesh.devices.size
    distributed = world > 1
    cdt = compute_dtype_of(cfg)

    def loss_fn(params, batch):
        _, logprobs, _, values = agent.apply(
            cast_floating(params, cdt), cast_floating(batch["obs"], cdt), actions=batch["actions"]
        )
        values = values.astype(jnp.float32)
        advantages = batch["advantages"]
        if cfg.algo.get("normalize_advantages", False):
            mu, std = advantages.mean(), advantages.std()
            if distributed:
                mu, std = jax.lax.pmean(mu, "data"), jax.lax.pmean(std, "data")
            advantages = (advantages - mu) / (std + 1e-8)
        pg = policy_loss(logprobs, advantages, cfg.algo.loss_reduction)
        vl = value_loss(values, batch["returns"], cfg.algo.loss_reduction)
        return pg + cfg.algo.vf_coef * vl, (pg, vl)

    def update(params, opt_state, data):
        grads, aux = jax.grad(loss_fn, has_aux=True)(params, data)
        if distributed:
            grads = jax.lax.pmean(grads, "data")
            aux = jax.lax.pmean(aux, "data")
        updates, new_opt_state = optimizer.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        # one NaN/Inf leaf poisons the global norm: a single scalar health
        # flag — computed once by health_stats when the health layer is on
        if health.enabled:
            hstats = health_stats(
                grads, updates, params, per_module=health.per_module, dead_eps=health.dead_eps
            )
            gnorm = hstats["grad_norm"]
            # GAE's returns = advantages + values, so the logged rollout
            # values are recoverable without threading a new batch key
            ev = explained_variance(data["returns"] - data["advantages"], data["returns"])
            if distributed:
                ev = jax.lax.pmean(ev, "data")
            hstats["value_ev"] = ev
        else:
            hstats = {}
            gnorm = optax.global_norm(grads)
        finite = finite_flag(gnorm, *aux)
        if sentinel.skip_update:
            params = select_finite(finite, new_params, params)
            opt_state = select_finite(finite, new_opt_state, opt_state)
        else:
            params, opt_state = new_params, new_opt_state
        return params, opt_state, jnp.stack([*aux, gnorm, 1.0 - finite.astype(jnp.float32)]), hstats

    if distributed:
        from sheeprl_tpu.parallel.compat import shard_map

        def sharded(params, opt_state, data):
            return shard_map(
                update,
                mesh=mesh,
                in_specs=(P(), P(), P("data")),
                out_specs=(P(), P(), P(), P()),
                check_vma=False,
            )(params, opt_state, data)

        return jax.jit(sharded, donate_argnums=(0, 1))
    return jax.jit(update, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg):
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    total_local = rollout_steps * num_envs
    if total_local % world_size != 0:
        raise ValueError(
            f"rollout_steps*num_envs ({total_local}) must be divisible by the number of devices ({world_size})"
        )

    rng_key = runtime.seed_everything(cfg.seed)
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    mlp_keys = cfg.algo.mlp_keys.encoder
    obs_keys = list(mlp_keys)
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    agent, params, _ = build_agent(
        runtime, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)
    base_opt = instantiate(cfg.algo.optimizer)
    chain = []
    if cfg.algo.max_grad_norm and cfg.algo.max_grad_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.algo.max_grad_norm))
    chain.append(base_opt)
    optimizer = optax.chain(*chain)
    opt_state = optimizer.init(params)
    if state and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_state,
            state["opt_state"],
        )

    from sheeprl_tpu.parallel.mesh import batch_sharding, replicated_sharding

    if world_size > 1:
        params = jax.device_put(params, replicated_sharding(runtime.mesh))
        opt_state = jax.device_put(opt_state, replicated_sharding(runtime.mesh))
        data_sharding = batch_sharding(runtime.mesh)
    else:
        data_sharding = None

    # telemetry instrumentation: watchdog + MFU FLOPs on the train step,
    # signature watch on the rollout policy (no shape-change injection here:
    # A2C's update consumes the whole batch, padding would alter the gradient)
    train_step = diag.instrument(
        "train_step",
        make_train_step(agent, optimizer, cfg, runtime.mesh),
        kind="train",
        donate_argnums=(0, 1),  # params, opt_state — audited at first dispatch
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_state)

    @jax.jit
    def policy_step(params, obs, key):
        actions, logprobs, _, values = agent.apply(params, obs, key=key)
        return actions, logprobs, values

    policy_step = diag.instrument("policy_step", policy_step, kind="rollout")
    # one staged h2d + one blocking fetch per vector step (see ppo.py)
    stage_sharding = obs_sharding(runtime.mesh if world_size > 1 else None)

    @jax.jit
    def value_step(params, obs):
        return agent.apply(params, obs, method="get_values")

    @jax.jit
    def gae_step(params, last_obs, rewards, values, dones):
        next_value = agent.apply(params, last_obs, method="get_values")
        return gae(rewards, values, dones, next_value, rollout_steps, cfg.algo.gamma, cfg.algo.gae_lambda)

    rb = ReplayBuffer(
        cfg.buffer.size,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=obs_keys,
    )
    diag.track_buffer("replay", rb)

    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(num_envs * rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1

    obs, _ = envs.reset(seed=cfg.seed)

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time"), diag.span("rollout"):
            for _ in range(rollout_steps):
                policy_step_count += num_envs
                diag.note_env_steps(num_envs)
                rng_key, step_key = jax.random.split(rng_key)
                torch_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs, sharding=stage_sharding)
                actions, logprobs, values = policy_step(params, torch_obs, step_key)
                actions_np, values_np = fetch_values(actions, values)
                if is_continuous:
                    env_actions = actions_np.reshape(num_envs, -1)
                elif is_multidiscrete:
                    env_actions = actions_np.astype(np.int64)
                else:
                    env_actions = actions_np[:, 0].astype(np.int64)

                # split-phase: env workers step while the host copies the
                # policy outputs + current obs into the step record (see
                # ppo.py — trajectories are identical to the serialized order)
                with diag.span("env_step_async"):
                    envs.step_async(env_actions)
                step_data: Dict[str, np.ndarray] = step_slab(
                    num_envs,
                    {**{k: obs[k] for k in obs_keys}, "actions": actions_np, "values": values_np},
                )
                with diag.span("env_wait"):
                    next_obs, rewards, terminated, truncated, info = envs.step_wait()
                dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)
                if cfg.env.clip_rewards:
                    rewards = np.tanh(rewards)

                if "final_obs" in info and np.any(truncated):
                    final_obs = info["final_obs"]
                    trunc_idx = np.nonzero(truncated)[0]
                    stacked = {k: np.stack([np.asarray(final_obs[i][k]) for i in trunc_idx]) for k in obs_keys}
                    t_obs = prepare_obs(stacked, mlp_keys=mlp_keys, num_envs=len(trunc_idx))
                    vals = np.asarray(value_step(params, t_obs))
                    rewards[trunc_idx] += cfg.algo.gamma * vals.reshape(-1, 1)

                step_data.update(step_slab(num_envs, {"rewards": rewards, "dones": dones}))
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                if "final_info" in info and "episode" in info["final_info"]:
                    ep = info["final_info"]["episode"]
                    mask = ep.get("_r", info["final_info"].get("_episode"))
                    if mask is not None and np.any(mask):
                        for r, l in zip(ep["r"][mask], ep["l"][mask]):
                            aggregator.update("Rewards/rew_avg", float(r))
                            aggregator.update("Game/ep_len_avg", float(l))

                obs = next_obs

        local = {k: np.asarray(rb[k][:rollout_steps]) for k in rb.buffer.keys()}
        torch_last_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)
        returns, advantages = gae_step(
            params,
            torch_last_obs,
            jnp.asarray(local["rewards"]),
            jnp.asarray(local["values"]),
            jnp.asarray(local["dones"]),
        )
        local["returns"] = np.asarray(returns)
        local["advantages"] = np.asarray(advantages)

        flat = {
            "obs": {k: local[k].reshape(total_local, *local[k].shape[2:]) for k in obs_keys},
            "actions": local["actions"].reshape(total_local, -1),
            "returns": local["returns"].reshape(total_local, -1),
            "advantages": local["advantages"].reshape(total_local, -1),
        }
        device_data = jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), data_sharding) if data_sharding else jnp.asarray(x),
            flat,
        )
        device_data = diag.maybe_inject_nan(iter_num, device_data)

        with timer("Time/train_time"), diag.span("train"):
            params, opt_state, losses, health = train_step(params, opt_state, device_data)
            # one blocking d2h for metrics + health stats together
            losses, health_host = fetch_values(losses, health)

        diag.on_health(policy_step_count, health_host)
        aggregator.update("Loss/policy_loss", float(losses[0]))
        aggregator.update("Loss/value_loss", float(losses[1]))
        aggregator.update("Grads/global_norm", float(losses[2]))
        diag.on_update(
            policy_step_count,
            {
                "Loss/policy_loss": float(losses[0]),
                "Loss/value_loss": float(losses[1]),
                "Grads/global_norm": float(losses[2]),
            },
            nonfinite=float(losses[3]),
        )

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if timers.get("Time/train_time", 0) > 0:
                metrics["Time/sps_train"] = iter_num / timers["Time/train_time"]
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "agent": jax.tree_util.tree_map(np.asarray, params),
                "opt_state": jax.tree_util.tree_map(np.asarray, opt_state),
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            with diag.span("checkpoint"):
                runtime.call("on_checkpoint_coupled", ckpt_path=ckpt_path, state=ckpt_state, replay_buffer=None)
            diag.on_checkpoint(policy_step_count, ckpt_path)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(agent.apply, params, test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    if cfg.model_manager.disabled is False and runtime.is_global_zero:  # pragma: no cover
        from sheeprl_tpu.utils.mlflow import log_models

        log_models(cfg, {"agent": params}, log_dir)
    logger.finalize()
    diag.close("completed")
