"""A2C helper surface (reference /root/reference/sheeprl/algos/a2c/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.ppo.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Grads/global_norm",
}
MODELS_TO_REGISTER = {"agent"}
