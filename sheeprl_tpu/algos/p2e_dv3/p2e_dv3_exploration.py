"""Plan2Explore-DV3 exploration (reference
/root/reference/sheeprl/algos/p2e_dv3/p2e_dv3_exploration.py:41-1059).

One jitted gradient step fuses the five reference phases into a single XLA
graph (the reference runs five separate backward passes on the torch tape):

1. world-model learning (identical to DreamerV3);
2. ensemble learning — N vmapped MLPs predict the next stochastic state from
   ``(posterior, recurrent, action)`` (reference :207-231);
3. exploration behaviour — imagination with the exploration actor; each
   exploration critic contributes a weighted normalized advantage, where
   ``intrinsic`` critics are rewarded by the ensembles' prediction variance
   (reference :252-303) and ``task`` critics by the world-model reward head;
4. per-critic two-hot value losses with their own target critics (:345-372);
5. task behaviour — standard DV3 actor/critic learning, trained zero-shot on
   the exploration data (:384-470).

Data parallelism follows the DV3 pattern: shard_map over the ``data`` mesh
axis, pmean'd grads, all-gathered Moments quantiles (one Moments state per
exploration critic + one for the task actor, reference :663-676).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3  # noqa: F401  (re-export for evaluate)
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _dreamer_main
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (
    chunked_dynamic_scan,
    init_moments_state,
    rssm_scan_spec,
    test,
    update_moments,
)
from sheeprl_tpu.algos.p2e_dv3.agent import build_agent
from sheeprl_tpu.algos.p2e_dv3.utils import (  # noqa: F401
    AGGREGATOR_KEYS,
    MODELS_TO_REGISTER,
    expand_exploration_metric_keys,
)
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    MSEDistribution,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.ops.numerics import compute_lambda_values
from sheeprl_tpu.parallel.dp import P, batch_spec, dp_axis, dp_jit, fold_key, pmean_tree
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.registry import register_algorithm

# filled by _build_agent before make_train_step runs (same single-controller
# stash pattern as the JEPA variant)
_P2E = {"ensemble_def": None, "critics_spec": None}


def metric_order(critics_spec) -> list:
    """Static order of the train-step metrics vector."""
    order = [
        "Loss/world_model_loss",
        "Loss/observation_loss",
        "Loss/reward_loss",
        "Loss/state_loss",
        "Loss/continue_loss",
        "State/kl",
        "Loss/ensemble_loss",
        "Loss/policy_loss_exploration",
        "Loss/policy_loss_task",
        "Loss/value_loss_task",
        "Grads/world_model",
        "Grads/ensemble",
        "Grads/actor_exploration",
        "Grads/actor_task",
        "Grads/critic_task",
    ]
    for name, _, reward_type in critics_spec:
        order.append(f"Loss/value_loss_exploration_{name}")
        order.append(f"Values_exploration/predicted_values_{name}")
        order.append(f"Values_exploration/lambda_values_{name}")
        if reward_type == "intrinsic":
            order.append(f"Rewards/intrinsic_{name}")
    return order


def make_train_step(
    world_model_def,
    actor_def,
    critic_def,
    optimizers,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    mesh=None,
):
    axis = dp_axis(mesh)
    cdt = compute_dtype_of(cfg)
    ensemble_def = _P2E["ensemble_def"]
    critics_spec = _P2E["critics_spec"]
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size
    recurrent_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    weights_sum = sum(w for _, w, _ in critics_spec)
    intrinsic_mult = cfg.algo.intrinsic_reward_multiplier
    # chunked sequence-parallel RSSM scan + unroll lever (inherited from the
    # shared DV3 config surface — see dreamer_v3.py::make_train_step)
    scan_unroll = int(cfg.algo.get("scan_unroll", 1))
    rssm_chunks, rssm_burn_in = rssm_scan_spec(cfg)

    def ensembles_apply(ens_params, x):
        return jax.vmap(lambda p: ensemble_def.apply(p, x))(ens_params)

    def imagine(wm_params, actor_params, posteriors, recurrents, k_a0, k_img):
        """Imagination rollout shared by the exploration and task phases
        (reference :234-250 / :384-400): returns [H+1, TB, ...] latents and
        the actions taken."""
        latent0 = jnp.concatenate([posteriors, recurrents], axis=-1)
        a0 = actor_def.apply(actor_params, jax.lax.stop_gradient(latent0), k_a0, False, method="act")

        def img_body(carry, key_t):
            prior, recurrent, actions = carry
            k_dyn, k_act = jax.random.split(key_t)
            prior, recurrent = world_model_def.apply(
                wm_params, prior, recurrent, actions, k_dyn, method="imagination"
            )
            latent = jnp.concatenate([prior, recurrent], axis=-1)
            actions = actor_def.apply(
                actor_params, jax.lax.stop_gradient(latent), k_act, False, method="act"
            )
            return (prior, recurrent, actions), (latent, actions)

        keys_h = jax.random.split(k_img, horizon)
        _, (latents_h, actions_h) = jax.lax.scan(
            img_body, (posteriors, recurrents, a0), keys_h, unroll=scan_unroll
        )
        trajectories = jnp.concatenate([latent0[None], latents_h], axis=0)
        actions = jnp.concatenate([a0[None], actions_h], axis=0)
        return trajectories, actions

    def train_step(params, opt_states, moments_state, batch, key, tau):
        T, B = batch["actions"].shape[:2]
        key = fold_key(key, axis)
        k_wm, k_img_e, k_a0_e, k_img_t, k_a0_t = jax.random.split(key, 5)

        # --- target Polyak updates (task + every exploration critic,
        # reference :911-925) --------------------------------------------
        params["target_critic_task"] = jax.tree_util.tree_map(
            lambda c, t: tau * c + (1 - tau) * t, params["critic_task"], params["target_critic_task"]
        )
        for name, _, _ in critics_spec:
            c = params["critics_exploration"][name]
            c["target_module"] = jax.tree_util.tree_map(
                lambda cm, tm: tau * cm + (1 - tau) * tm, c["module"], c["target_module"]
            )

        target_obs = {k: batch[k] for k in set(cnn_dec_keys + mlp_dec_keys)}  # fp32 targets
        batch_obs = cast_floating(target_obs, cdt)
        batch_actions = jnp.concatenate(
            [jnp.zeros_like(batch["actions"][:1]), batch["actions"][:-1]], axis=0
        ).astype(cdt)
        is_first = batch["is_first"].at[0].set(1.0).astype(cdt)

        # ---------------- 1) DYNAMIC LEARNING (as DV3) --------------------
        def wm_loss_fn(wm_params):
            wm_params = cast_floating(wm_params, cdt)
            embedded = world_model_def.apply(wm_params, batch_obs, method="encode")

            def scan_body(carry, x):
                posterior, recurrent = carry
                action_t, embed_t, is_first_t, key_t = x
                recurrent, posterior, _, post_logits, prior_logits = world_model_def.apply(
                    wm_params, posterior, recurrent, action_t, embed_t, is_first_t, key_t, method="dynamic"
                )
                return (posterior, recurrent), (recurrent, posterior, post_logits, prior_logits)

            recurrents, posteriors, post_logits, prior_logits = chunked_dynamic_scan(
                scan_body,
                batch_actions,
                embedded,
                is_first,
                k_wm,
                stoch_flat=stoch_flat,
                recurrent_size=recurrent_size,
                cdt=cdt,
                chunks=rssm_chunks,
                burn_in=rssm_burn_in,
                stored_recurrent=batch.get("rssm_recurrent"),
                stored_posterior=batch.get("rssm_posterior"),
                stored_valid=batch.get("rssm_valid"),
                unroll=scan_unroll,
            )
            latents = jnp.concatenate([posteriors, recurrents], axis=-1)
            recon = world_model_def.apply(wm_params, latents, method="decode")
            po = {k: MSEDistribution(recon[k], dims=len(recon[k].shape[2:])) for k in cnn_dec_keys}
            po.update(
                {k: SymlogDistribution(recon[k], dims=len(recon[k].shape[2:])) for k in mlp_dec_keys}
            )
            pr = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, latents, method="reward_logits"), dims=1
            )
            pc = Bernoulli(
                world_model_def.apply(wm_params, latents, method="continue_logits"), event_dims=1
            )
            continues_targets = 1 - batch["terminated"]
            pl = prior_logits.reshape(T, B, wm_cfg.stochastic_size, wm_cfg.discrete_size)
            ql = post_logits.reshape(T, B, wm_cfg.stochastic_size, wm_cfg.discrete_size)
            rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po,
                target_obs,
                pr,
                batch["rewards"],
                pl,
                ql,
                wm_cfg.kl_dynamic,
                wm_cfg.kl_representation,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                pc,
                continues_targets,
                wm_cfg.continue_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrents": recurrents,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
            }
            return rec_loss, aux

        (rec_loss, aux), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(params["world_model"])
        wm_grads = pmean_tree(wm_grads, axis)
        updates, opt_states["world_model"] = optimizers["world_model"].update(
            wm_grads, opt_states["world_model"], params["world_model"]
        )
        params["world_model"] = optax.apply_updates(params["world_model"], updates)
        wm_params = cast_floating(params["world_model"], cdt)

        posteriors = jax.lax.stop_gradient(aux["posteriors"])  # [T, B, S]
        recurrents = jax.lax.stop_gradient(aux["recurrents"])  # [T, B, R]

        # ---------------- 2) ENSEMBLE LEARNING (reference :207-231) -------
        def ens_loss_fn(ens_params):
            inp = jnp.concatenate([posteriors, recurrents, batch["actions"].astype(cdt)], axis=-1)
            outs = ensembles_apply(cast_floating(ens_params, cdt), inp)[:, :-1]  # [N, T-1, B, S]
            target = posteriors[1:]
            # sum over ensemble members of the MSE "log prob" loss
            lp = MSEDistribution(outs, dims=1).log_prob(
                jnp.broadcast_to(target[None], outs.shape)
            )  # [N, T-1, B]
            return -jnp.mean(lp, axis=(1, 2)).sum()

        ens_loss, ens_grads = jax.value_and_grad(ens_loss_fn)(params["ensembles"])
        ens_grads = pmean_tree(ens_grads, axis)
        updates, opt_states["ensembles"] = optimizers["ensembles"].update(
            ens_grads, opt_states["ensembles"], params["ensembles"]
        )
        params["ensembles"] = optax.apply_updates(params["ensembles"], updates)

        flat_post = posteriors.reshape(T * B, stoch_flat)
        flat_rec = recurrents.reshape(T * B, recurrent_size)
        true_continue = (1 - batch["terminated"]).reshape(T * B, 1)

        # ---------------- 3) EXPLORATION BEHAVIOUR (reference :233-343) ----
        def actor_expl_loss_fn(actor_params, moments_expl):
            actor_params = cast_floating(actor_params, cdt)
            trajectories, actions = imagine(wm_params, actor_params, flat_post, flat_rec, k_a0_e, k_img_e)
            continues = Bernoulli(
                world_model_def.apply(wm_params, trajectories, method="continue_logits"), event_dims=1
            ).mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, axis=0) / gamma)

            # intrinsic reward: ensemble disagreement (unbiased variance as
            # torch's Tensor.var, reference :259-263)
            ens_in = jax.lax.stop_gradient(jnp.concatenate([trajectories, actions], axis=-1))
            preds = ensembles_apply(cast_floating(params["ensembles"], cdt), ens_in).astype(
                jnp.float32
            )  # [N, H+1, TB, S]; disagreement variance in fp32
            intrinsic_reward = (
                jnp.var(preds, axis=0, ddof=1).mean(-1, keepdims=True) * intrinsic_mult
            )
            task_reward = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, trajectories, method="reward_logits"), dims=1
            ).mean

            advantage = 0.0
            new_moments = {}
            critic_aux = {}
            for name, weight, reward_type in critics_spec:
                values = TwoHotEncodingDistribution(
                    critic_def.apply(
                        cast_floating(params["critics_exploration"][name]["module"], cdt), trajectories
                    ),
                    dims=1,
                ).mean
                reward = intrinsic_reward if reward_type == "intrinsic" else task_reward
                lam = compute_lambda_values(
                    reward[1:], values[1:], continues[1:] * gamma, lmbda=cfg.algo.lmbda
                )
                offset, invscale, new_moments[name] = update_moments(
                    moments_expl[name],
                    lam,
                    cfg.algo.actor.moments.decay,
                    cfg.algo.actor.moments.max,
                    cfg.algo.actor.moments.percentile.low,
                    cfg.algo.actor.moments.percentile.high,
                    axis_name=axis,
                )
                baseline = values[:-1]
                advantage = advantage + ((lam - offset) / invscale - (baseline - offset) / invscale) * (
                    weight / weights_sum
                )
                critic_aux[name] = {
                    "lambda_values": jax.lax.stop_gradient(lam),
                    "predicted_values": jnp.mean(jax.lax.stop_gradient(values)),
                    "intrinsic_reward": jnp.mean(jax.lax.stop_gradient(reward)),
                }

            log_probs, entropies = actor_def.apply(
                actor_params,
                jax.lax.stop_gradient(trajectories),
                jax.lax.stop_gradient(actions),
                method="log_prob_entropy",
            )
            if is_continuous:
                objective = advantage
            else:
                objective = log_probs[:-1] * jax.lax.stop_gradient(advantage)
            entropy = cfg.algo.actor.ent_coef * entropies
            loss = -jnp.mean(discount[:-1] * (objective + entropy[:-1]))
            aux2 = {
                "trajectories": jax.lax.stop_gradient(trajectories),
                "discount": discount,
                "moments": new_moments,
                "critic_aux": critic_aux,
            }
            return loss, aux2

        (policy_loss_expl, aux_e), actor_expl_grads = jax.value_and_grad(actor_expl_loss_fn, has_aux=True)(
            params["actor_exploration"], moments_state["exploration"]
        )
        actor_expl_grads = pmean_tree(actor_expl_grads, axis)
        updates, opt_states["actor_exploration"] = optimizers["actor_exploration"].update(
            actor_expl_grads, opt_states["actor_exploration"], params["actor_exploration"]
        )
        params["actor_exploration"] = optax.apply_updates(params["actor_exploration"], updates)
        moments_state["exploration"] = aux_e["moments"]

        # ---------------- 4) EXPLORATION CRITICS (reference :345-372) ------
        expl_traj = aux_e["trajectories"]
        expl_discount = aux_e["discount"]
        critic_metrics = []
        for name, _, reward_type in critics_spec:
            lam = aux_e["critic_aux"][name]["lambda_values"]

            def critic_loss_fn(critic_params):
                qv = TwoHotEncodingDistribution(
                    critic_def.apply(cast_floating(critic_params, cdt), expl_traj[:-1]), dims=1
                )
                target_vals = TwoHotEncodingDistribution(
                    critic_def.apply(
                        cast_floating(params["critics_exploration"][name]["target_module"], cdt),
                        expl_traj[:-1],
                    ),
                    dims=1,
                ).mean
                loss = -qv.log_prob(lam) - qv.log_prob(jax.lax.stop_gradient(target_vals))
                return jnp.mean(loss * expl_discount[:-1, ..., 0])

            vloss, cgrads = jax.value_and_grad(critic_loss_fn)(
                params["critics_exploration"][name]["module"]
            )
            cgrads = pmean_tree(cgrads, axis)
            updates, opt_states["critics_exploration"][name] = optimizers["critics_exploration"].update(
                cgrads, opt_states["critics_exploration"][name], params["critics_exploration"][name]["module"]
            )
            params["critics_exploration"][name]["module"] = optax.apply_updates(
                params["critics_exploration"][name]["module"], updates
            )
            critic_metrics.append(vloss)
            critic_metrics.append(aux_e["critic_aux"][name]["predicted_values"])
            critic_metrics.append(jnp.mean(lam))
            if reward_type == "intrinsic":
                critic_metrics.append(aux_e["critic_aux"][name]["intrinsic_reward"])

        # ---------------- 5) TASK BEHAVIOUR (zero-shot, reference :384-470) -
        def actor_task_loss_fn(actor_params, moments_task):
            actor_params = cast_floating(actor_params, cdt)
            trajectories, actions = imagine(wm_params, actor_params, flat_post, flat_rec, k_a0_t, k_img_t)
            predicted_values = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(params["critic_task"], cdt), trajectories), dims=1
            ).mean
            predicted_rewards = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, trajectories, method="reward_logits"), dims=1
            ).mean
            continues = Bernoulli(
                world_model_def.apply(wm_params, trajectories, method="continue_logits"), event_dims=1
            ).mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)
            lam = compute_lambda_values(
                predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda=cfg.algo.lmbda
            )
            discount = jax.lax.stop_gradient(jnp.cumprod(continues * gamma, axis=0) / gamma)
            offset, invscale, new_moments = update_moments(
                moments_task,
                lam,
                cfg.algo.actor.moments.decay,
                cfg.algo.actor.moments.max,
                cfg.algo.actor.moments.percentile.low,
                cfg.algo.actor.moments.percentile.high,
                axis_name=axis,
            )
            baseline = predicted_values[:-1]
            advantage = (lam - offset) / invscale - (baseline - offset) / invscale
            log_probs, entropies = actor_def.apply(
                actor_params,
                jax.lax.stop_gradient(trajectories),
                jax.lax.stop_gradient(actions),
                method="log_prob_entropy",
            )
            if is_continuous:
                objective = advantage
            else:
                objective = log_probs[:-1] * jax.lax.stop_gradient(advantage)
            entropy = cfg.algo.actor.ent_coef * entropies
            loss = -jnp.mean(discount[:-1] * (objective + entropy[:-1]))
            aux3 = {
                "trajectories": jax.lax.stop_gradient(trajectories),
                "lambda_values": jax.lax.stop_gradient(lam),
                "discount": discount,
                "moments": new_moments,
            }
            return loss, aux3

        (policy_loss_task, aux_t), actor_task_grads = jax.value_and_grad(actor_task_loss_fn, has_aux=True)(
            params["actor_task"], moments_state["task"]
        )
        actor_task_grads = pmean_tree(actor_task_grads, axis)
        updates, opt_states["actor_task"] = optimizers["actor_task"].update(
            actor_task_grads, opt_states["actor_task"], params["actor_task"]
        )
        params["actor_task"] = optax.apply_updates(params["actor_task"], updates)
        moments_state["task"] = aux_t["moments"]

        def critic_task_loss_fn(critic_params):
            qv = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(critic_params, cdt), aux_t["trajectories"][:-1]), dims=1
            )
            target_vals = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(params["target_critic_task"], cdt), aux_t["trajectories"][:-1]),
                dims=1,
            ).mean
            loss = -qv.log_prob(aux_t["lambda_values"]) - qv.log_prob(jax.lax.stop_gradient(target_vals))
            return jnp.mean(loss * aux_t["discount"][:-1, ..., 0])

        value_loss_task, critic_task_grads = jax.value_and_grad(critic_task_loss_fn)(params["critic_task"])
        critic_task_grads = pmean_tree(critic_task_grads, axis)
        updates, opt_states["critic_task"] = optimizers["critic_task"].update(
            critic_task_grads, opt_states["critic_task"], params["critic_task"]
        )
        params["critic_task"] = optax.apply_updates(params["critic_task"], updates)

        metrics = jnp.stack(
            [
                rec_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                ens_loss,
                policy_loss_expl,
                policy_loss_task,
                value_loss_task,
                optax.global_norm(wm_grads),
                optax.global_norm(ens_grads),
                optax.global_norm(actor_expl_grads),
                optax.global_norm(actor_task_grads),
                optax.global_norm(critic_task_grads),
                *critic_metrics,
            ]
        )
        metrics = pmean_tree(metrics, axis)
        return params, opt_states, moments_state, metrics

    from sheeprl_tpu.parallel.dp import fsdp_min_shard_bytes

    return dp_jit(
        train_step,
        mesh,
        in_specs=(P(), P(), P(), batch_spec(batch_axis=1), P(), P()),
        out_specs=(P(), P(), P(), P()),
        donate_argnums=(0, 1, 2),
        min_shard_bytes=fsdp_min_shard_bytes(cfg),
    )


def _build_agent(runtime, actions_dim, is_continuous, cfg, obs_space, state):
    world_model_def, actor_def, critic_def, ensemble_def, params, critics_spec = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        state["world_model"] if state else None,
        state["ensembles"] if state else None,
        state["actor_task"] if state else None,
        state["critic_task"] if state else None,
        state["target_critic_task"] if state else None,
        state["actor_exploration"] if state else None,
        state["critics_exploration"] if state else None,
    )
    _P2E["ensemble_def"] = ensemble_def
    _P2E["critics_spec"] = critics_spec
    return world_model_def, actor_def, critic_def, params


def _make_optimizers(cfg, params, agent_state):
    """World/actor_task/critic_task/actor_exploration/ensembles optimizers +
    one shared-definition optimizer per exploration critic
    (reference p2e_dv3_exploration.py:617-660)."""
    chain = lambda clip, opt_cfg: optax.chain(  # noqa: E731
        optax.clip_by_global_norm(clip), instantiate(opt_cfg)
    )
    optimizers = {
        "world_model": chain(cfg.algo.world_model.clip_gradients, cfg.algo.world_model.optimizer),
        "actor_task": chain(cfg.algo.actor.clip_gradients, cfg.algo.actor.optimizer),
        "critic_task": chain(cfg.algo.critic.clip_gradients, cfg.algo.critic.optimizer),
        "actor_exploration": chain(cfg.algo.actor.clip_gradients, cfg.algo.actor.optimizer),
        "ensembles": chain(cfg.algo.ensembles.clip_gradients, cfg.algo.ensembles.optimizer),
        # the reference instantiates each exploration-critic optimizer from
        # cfg.algo.critic.optimizer (p2e_dv3_exploration.py:623-626)
        "critics_exploration": chain(cfg.algo.critic.clip_gradients, cfg.algo.critic.optimizer),
    }
    opt_states = {
        "world_model": optimizers["world_model"].init(params["world_model"]),
        "actor_task": optimizers["actor_task"].init(params["actor_task"]),
        "critic_task": optimizers["critic_task"].init(params["critic_task"]),
        "actor_exploration": optimizers["actor_exploration"].init(params["actor_exploration"]),
        "ensembles": optimizers["ensembles"].init(params["ensembles"]),
        "critics_exploration": {
            k: optimizers["critics_exploration"].init(v["module"])
            for k, v in params["critics_exploration"].items()
        },
    }
    if agent_state and "opt_states" in agent_state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            agent_state["opt_states"],
        )
    return optimizers, opt_states


def _init_moments(cfg, agent_state):
    critics_spec = _P2E["critics_spec"]
    moments = {
        "task": init_moments_state(),
        "exploration": {name: init_moments_state() for name, _, _ in critics_spec},
    }
    if agent_state and "moments" in agent_state:
        moments = jax.tree_util.tree_map(jnp.asarray, agent_state["moments"])
    return moments


def _player_actor(cfg):
    actor_type = cfg.algo.player.actor_type

    def fn(params, has_trained):
        return params["actor_exploration"] if actor_type == "exploration" else params["actor_task"]

    return fn


def _zero_shot_test(player, params, runtime, cfg, log_dir):
    """Final task test with the *task* actor (reference :1032-1037)."""
    return test(
        player, params["world_model"], params["actor_task"], runtime, cfg, log_dir, "zero-shot", greedy=False
    )


@register_algorithm()
def main(runtime, cfg):
    # exploration always plays with the exploration actor (reference :530)
    cfg.algo.player.actor_type = "exploration"
    from sheeprl_tpu.algos.p2e_dv3.agent import exploration_critics_spec

    critics_spec = exploration_critics_spec(cfg)
    expand_exploration_metric_keys(cfg, [name for name, _, _ in critics_spec])
    return _dreamer_main(
        runtime,
        cfg,
        _build_agent,
        make_train_step,
        make_optimizers_fn=_make_optimizers,
        init_moments_fn=_init_moments,
        player_actor_fn=_player_actor(cfg),
        metric_order=metric_order(critics_spec),
        final_test_fn=_zero_shot_test,
    )
