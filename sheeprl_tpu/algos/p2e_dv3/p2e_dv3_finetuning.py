"""Plan2Explore-DV3 finetuning (reference
/root/reference/sheeprl/algos/p2e_dv3/p2e_dv3_finetuning.py:28-477).

Bootstraps from an **exploration checkpoint**
(``checkpoint.exploration_ckpt_path``): world model, task actor/critic (and
their optimizer states + task Moments) come from the exploration phase; the
training loop itself is standard DreamerV3 (the reference literally imports
``dreamer_v3.train``).  The player acts with the *exploration* actor during
prefill and switches to the *task* actor at the first gradient step
(reference :350-354).

Config surgery: the model/topology fields must match the exploration run, so
they are copied from the exploration run's archived ``config.yaml``
(reference cli.py:117-148 does this in the CLI; here it lives in the
algorithm main so the CLI stays generic).
"""

from __future__ import annotations

import pathlib

import jax
import jax.numpy as jnp
import yaml

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import (
    METRIC_ORDER,
    _default_make_optimizers,
    _dreamer_main,
    make_train_step,
)
from sheeprl_tpu.algos.dreamer_v3.utils import AGGREGATOR_KEYS, init_moments_state  # noqa: F401
from sheeprl_tpu.algos.p2e_dv3.agent import build_agent
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.utils import dotdict

MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def load_exploration_cfg(cfg) -> dotdict:
    ckpt_path = pathlib.Path(cfg.checkpoint.exploration_ckpt_path)
    cfg_path = ckpt_path.parent.parent / "config.yaml"
    if not cfg_path.is_file():
        raise FileNotFoundError(
            f"Archived exploration config not found at '{cfg_path}' "
            "(checkpoint.exploration_ckpt_path must point inside an exploration run dir)"
        )
    with open(cfg_path) as fp:
        return dotdict(yaml.safe_load(fp))


def apply_exploration_cfg(cfg, exploration_cfg) -> None:
    """Copy the model/topology/env fields that must match the exploration run
    (reference cli.py:117-148 + p2e_dv3_finetuning.py:45-71)."""
    if exploration_cfg.env.id != cfg.env.id:
        raise ValueError(
            "Finetuning must use the exploration environment: "
            f"got '{cfg.env.id}', exploration used '{exploration_cfg.env.id}'"
        )
    for k in (
        "gamma",
        "lmbda",
        "horizon",
        "layer_norm",
        "dense_units",
        "mlp_layers",
        "dense_act",
        "cnn_act",
        "unimix",
        "hafner_initialization",
        "world_model",
        "actor",
        "critic",
        "cnn_keys",
        "mlp_keys",
    ):
        if k in exploration_cfg.algo:
            cfg.algo[k] = exploration_cfg.algo[k]
    for k in (
        "screen_size",
        "action_repeat",
        "grayscale",
        "clip_rewards",
        "frame_stack_dilation",
        "max_episode_steps",
        "reward_as_observation",
    ):
        if k in exploration_cfg.env:
            cfg.env[k] = exploration_cfg.env[k]
    if cfg.buffer.get("load_from_exploration") and exploration_cfg.buffer.checkpoint:
        cfg.env.num_envs = exploration_cfg.env.num_envs


def _build_agent(runtime, actions_dim, is_continuous, cfg, obs_space, state):
    """Build the DV3-layout agent from a P2E state (exploration checkpoint,
    or a finetuning checkpoint when resuming — the latter stores DV3-style
    keys plus ``actor_exploration``)."""
    is_finetune_ckpt = state is not None and "actor" in state
    wm_state = state["world_model"] if state else None
    actor_task_state = (state["actor"] if is_finetune_ckpt else state["actor_task"]) if state else None
    critic_task_state = (state["critic"] if is_finetune_ckpt else state["critic_task"]) if state else None
    target_state = (
        (state["target_critic"] if is_finetune_ckpt else state["target_critic_task"]) if state else None
    )
    world_model_def, actor_def, critic_def, _, p2e_params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        wm_state,
        None,
        actor_task_state,
        critic_task_state,
        target_state,
        state["actor_exploration"] if state else None,
        None,
    )
    params = {
        "world_model": p2e_params["world_model"],
        "actor": p2e_params["actor_task"],
        "critic": p2e_params["critic_task"],
        "target_critic": p2e_params["target_critic_task"],
        "actor_exploration": p2e_params["actor_exploration"],
    }
    return world_model_def, actor_def, critic_def, params


def _make_optimizers(cfg, params, agent_state):
    """DV3 trio; restore from the exploration checkpoint's task-optimizer
    states (keys ``actor_task``/``critic_task``) or a finetuning resume
    checkpoint (DV3 keys)."""
    optimizers, opt_states = _default_make_optimizers(cfg, params, None)
    if agent_state and "opt_states" in agent_state:
        saved = agent_state["opt_states"]
        mapped = {
            "world_model": saved["world_model"],
            "actor": saved["actor_task"] if "actor_task" in saved else saved["actor"],
            "critic": saved["critic_task"] if "critic_task" in saved else saved["critic"],
        }
        opt_states = jax.tree_util.tree_map(
            lambda ref, s: jnp.asarray(s, dtype=getattr(ref, "dtype", None)), opt_states, mapped
        )
    return optimizers, opt_states


def _init_moments(cfg, agent_state):
    moments = init_moments_state()
    if agent_state and "moments" in agent_state:
        saved = agent_state["moments"]
        if isinstance(saved, dict) and "task" in saved:  # exploration ckpt layout
            saved = saved["task"]
        moments = jax.tree_util.tree_map(jnp.asarray, saved)
    return moments


def _player_actor(cfg):
    def fn(params, has_trained):
        # prefill with the exploration actor, then switch to the task actor
        # at the first gradient step (reference :350-354)
        if has_trained or cfg.algo.player.actor_type == "task":
            return params["actor"]
        return params["actor_exploration"]

    return fn


@register_algorithm()
def main(runtime, cfg):
    exploration_cfg = load_exploration_cfg(cfg)
    apply_exploration_cfg(cfg, exploration_cfg)

    def load_agent_state_fn(runtime, cfg):
        return runtime.load(cfg.checkpoint.exploration_ckpt_path)

    return _dreamer_main(
        runtime,
        cfg,
        _build_agent,
        make_train_step,
        make_optimizers_fn=_make_optimizers,
        init_moments_fn=_init_moments,
        player_actor_fn=_player_actor(cfg),
        metric_order=METRIC_ORDER,
        load_agent_state_fn=load_agent_state_fn,
    )
