"""P2E-DV3 helpers (reference /root/reference/sheeprl/algos/p2e_dv3/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import AGGREGATOR_KEYS as AGGREGATOR_KEYS_DV3

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Loss/ensemble_loss",
    "Loss/policy_loss_exploration",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Grads/world_model",
    "Grads/ensemble",
    "Grads/actor_exploration",
    "Grads/actor_task",
    "Grads/critic_task",
    # generic per-exploration-critic keys; the exploration main expands them
    # to `<key>_<critic_name>` (reference p2e_dv3_exploration.py:683-706)
    "Loss/value_loss_exploration",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/critic_exploration",
    "Rewards/intrinsic",
}.union(AGGREGATOR_KEYS_DV3)

MODELS_TO_REGISTER = {
    "world_model",
    "ensembles",
    "actor_exploration",
    "actor_task",
    "critic_task",
    "target_critic_task",
    "critics_exploration",
    "moments_task",
    "moments_exploration",
}

GENERIC_CRITIC_METRICS = (
    "Loss/value_loss_exploration",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/critic_exploration",
    "Rewards/intrinsic",
)


def expand_exploration_metric_keys(cfg, critic_names) -> None:
    """Replace the generic exploration-critic metric configs with one entry
    per critic (reference p2e_dv3_exploration.py:683-706)."""
    metrics = cfg.metric.aggregator.get("metrics", {})
    for generic in GENERIC_CRITIC_METRICS:
        template = metrics.pop(generic, None)
        if template is None:
            continue
        for name in critic_names:
            metrics[f"{generic}_{name}"] = template
    cfg.metric.aggregator.metrics = metrics
