"""Plan2Explore-DV3 agent (reference /root/reference/sheeprl/algos/p2e_dv3/agent.py:27-223).

On top of the DreamerV3 stack this adds:

- an **exploration actor** (same ``Actor`` module, its own params);
- a dict of **exploration critics** keyed by name, each with a weight, a
  reward type (``intrinsic`` | ``task``), its own params and a target copy
  (reference agent.py:119-156);
- an **ensemble** of N MLPs predicting the next stochastic state from
  ``(posterior, recurrent, action)`` whose disagreement (variance) is the
  intrinsic reward (reference agent.py:174-204).

TPU-native design note: the reference keeps the N ensembles as an
``nn.ModuleList`` looped in Python; here the N parameter sets are **stacked
on a leading axis and applied with ``jax.vmap``** — one fused XLA computation
for all members, which is how an ensemble should meet the MXU.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Critic,
    DenseStack,
    build_agent as dv3_build_agent,
    trunc_normal_init,
    uniform_init,
)


class Ensemble(nn.Module):
    """One ensemble member: MLP ``(latent, action) -> next stochastic state``
    (reference agent.py:181-199 builds N of these)."""

    output_dim: int
    dense_units: int
    mlp_layers: int
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True
    hafner_initialization: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.dense_units, self.mlp_layers, self.eps, self.act, self.layer_norm)(x)
        init = uniform_init(0.0) if self.hafner_initialization else trunc_normal_init
        return nn.Dense(self.output_dim, kernel_init=init)(x)


def exploration_critics_spec(cfg) -> List[Tuple[str, float, str]]:
    """Sorted ``(name, weight, reward_type)`` for every critic with weight>0
    (reference agent.py:121-141).  At least one must be intrinsic."""
    spec = []
    for k in sorted(cfg.algo.critics_exploration):
        v = cfg.algo.critics_exploration[k]
        if v.weight > 0:
            spec.append((k, float(v.weight), str(v.reward_type)))
    if not any(rt == "intrinsic" for _, _, rt in spec):
        raise RuntimeError("You must specify at least one intrinsic critic (`reward_type='intrinsic'`)")
    return spec


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    world_model_state: Optional[Dict[str, Any]] = None,
    ensembles_state: Optional[Dict[str, Any]] = None,
    actor_task_state: Optional[Dict[str, Any]] = None,
    critic_task_state: Optional[Dict[str, Any]] = None,
    target_critic_task_state: Optional[Dict[str, Any]] = None,
    actor_exploration_state: Optional[Dict[str, Any]] = None,
    critics_exploration_state: Optional[Dict[str, Any]] = None,
):
    """Returns ``(world_model_def, actor_def, critic_def, ensemble_def,
    params, critics_spec)``.

    ``params`` keys: world_model, actor_task, critic_task, target_critic_task,
    actor_exploration, critics_exploration ({k: {module, target_module}}),
    ensembles (leading-axis-stacked member params).
    """
    world_model_def, actor_def, critic_def, dv3_params = dv3_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
        target_critic_task_state,
    )
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size
    latent_state_size = stoch_flat + wm_cfg.recurrent_model.recurrent_state_size
    eps = float(cfg.algo.mlp_layer_norm.kw.get("eps", 1e-3)) if cfg.algo.get("mlp_layer_norm") else 1e-3

    key = jax.random.PRNGKey(int(cfg.seed or 0) + 17)
    k_actor_expl, k_crit, k_ens = jax.random.split(key, 3)
    sample_latent = jnp.zeros((1, latent_state_size), jnp.float32)

    # exploration actor: same definition, freshly initialized params
    actor_exploration_params = actor_def.init(k_actor_expl, sample_latent)
    if actor_exploration_state is not None:
        actor_exploration_params = jax.tree_util.tree_map(jnp.asarray, actor_exploration_state)

    # exploration critics (same Critic module as the task critic)
    critics_spec = exploration_critics_spec(cfg)
    critics_params: Dict[str, Dict[str, Any]] = {}
    for i, (name, _, _) in enumerate(critics_spec):
        cp = critic_def.init(jax.random.fold_in(k_crit, i), sample_latent)
        critics_params[name] = {"module": cp, "target_module": jax.tree_util.tree_map(jnp.copy, cp)}
    if critics_exploration_state is not None:
        critics_params = jax.tree_util.tree_map(jnp.asarray, dict(critics_exploration_state))

    # vmapped ensemble: stack N independent inits on a leading axis
    ens_cfg = cfg.algo.ensembles
    ensemble_def = Ensemble(
        output_dim=stoch_flat,
        dense_units=ens_cfg.dense_units,
        mlp_layers=ens_cfg.mlp_layers,
        eps=eps,
        hafner_initialization=cfg.algo.hafner_initialization,
    )
    sample_in = jnp.zeros((1, latent_state_size + int(sum(actions_dim))), jnp.float32)
    member_keys = jax.random.split(k_ens, int(ens_cfg.n))
    ensembles_params = jax.vmap(lambda k: ensemble_def.init(k, sample_in))(member_keys)
    if ensembles_state is not None:
        ensembles_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)

    params = {
        "world_model": dv3_params["world_model"],
        "actor_task": dv3_params["actor"],
        "critic_task": dv3_params["critic"],
        "target_critic_task": dv3_params["target_critic"],
        "actor_exploration": actor_exploration_params,
        "critics_exploration": critics_params,
        "ensembles": ensembles_params,
    }
    return world_model_def, actor_def, critic_def, ensemble_def, params, critics_spec
