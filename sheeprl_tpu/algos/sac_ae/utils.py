"""SAC-AE helper surface (reference /root/reference/sheeprl/algos/sac_ae/utils.py)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Loss/reconstruction_loss",
}
MODELS_TO_REGISTER = {"agent", "encoder", "decoder"}


def preprocess_obs(obs: jax.Array, key: jax.Array, bits: int = 8) -> jax.Array:
    """Bit-reduction + dequantization noise (reference utils.py:68-76,
    https://arxiv.org/abs/1807.03039)."""
    bins = 2**bits
    if bits < 8:
        obs = jnp.floor(obs / 2 ** (8 - bits))
    obs = obs / bins
    obs = obs + jax.random.uniform(key, obs.shape) / bins
    return obs - 0.5


def prepare_obs(
    obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), mlp_keys: Sequence[str] = (), num_envs: int = 1
) -> Dict[str, jax.Array]:
    out: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        v = np.asarray(obs[k])
        out[k] = jnp.asarray(v, jnp.float32).reshape(num_envs, -1, *v.shape[-2:]) / 255.0
    for k in mlp_keys:
        out[k] = jnp.asarray(np.asarray(obs[k]), jnp.float32).reshape(num_envs, -1)
    return out


def test(encoder_apply, actor_apply, encoder_params, actor_params, env, runtime, cfg, log_dir: str) -> float:
    done = False
    cumulative_rew = 0.0
    obs, _ = env.reset(seed=cfg.seed)
    while not done:
        torch_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, mlp_keys=cfg.algo.mlp_keys.encoder)
        features = encoder_apply(encoder_params, torch_obs)
        action = actor_apply(actor_params, features, method="greedy_action")
        obs, reward, terminated, truncated, _ = env.step(np.asarray(action).reshape(env.action_space.shape))
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    env.close()
    return cumulative_rew
