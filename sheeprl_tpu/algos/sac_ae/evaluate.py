"""SAC-AE evaluation entrypoint (reference /root/reference/sheeprl/algos/sac_ae/evaluate.py)."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.sac_ae.agent import build_agent
from sheeprl_tpu.algos.sac_ae.utils import test
from sheeprl_tpu.envs.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="sac_ae")
def evaluate_sac_ae(runtime, cfg, state: Dict[str, Any]) -> None:
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    observation_space = env.observation_space
    action_space = env.action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    encoder_def, _, actor_def, _, params, _ = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"]
    )
    cumulative_rew = test(
        encoder_def.apply, actor_def.apply, params["encoder"], params["actor"], env, runtime, cfg, log_dir
    )
    logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    logger.finalize()
