"""SAC-AE agent — TPU-native re-design of
/root/reference/sheeprl/algos/sac_ae/agent.py:26-640 (SAC+AE,
https://arxiv.org/abs/1910.01741).

Pixel SAC with a convolutional autoencoder: the critic trains the shared
encoder, the actor sees detached features, and a decoder reconstruction loss
(+ L2 latent penalty) regularizes the representation.  Convs run NHWC; the
final transposed conv uses a 4x4 kernel (instead of the reference's 3x3 +
output_padding) to reproduce the exact 64x64 output shape, which XLA tiles
better anyway.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.models.blocks import MLP

LOG_STD_MAX = 2.0
LOG_STD_MIN = -10.0


class SACAEEncoder(nn.Module):
    """4-conv encoder (k3, strides 2/1/1/1) + LayerNorm-tanh projection
    (reference agent.py:26-87) fused with an MLP branch for vector keys."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    features_dim: int = 64
    channels_multiplier: int = 1
    dense_units: int = 64
    mlp_layers: int = 2

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array], detach_encoder_features: bool = False) -> jax.Array:
        feats = []
        if self.cnn_keys:
            x = jnp.concatenate([obs[k] for k in self.cnn_keys], axis=-3)
            lead = x.shape[:-3]
            x = x.reshape((-1,) + x.shape[-3:])
            x = jnp.transpose(x, (0, 2, 3, 1))
            for stride in (2, 1, 1, 1):
                x = nn.Conv(32 * self.channels_multiplier, (3, 3), strides=(stride, stride), padding="VALID")(x)
                x = jax.nn.relu(x)
            x = x.reshape(lead + (-1,))
            if detach_encoder_features:
                x = jax.lax.stop_gradient(x)
            x = nn.Dense(self.features_dim)(x)
            x = nn.LayerNorm()(x)
            feats.append(jnp.tanh(x))
        if self.mlp_keys:
            v = jnp.concatenate([obs[k] for k in self.mlp_keys], axis=-1)
            v = MLP(hidden_sizes=[self.dense_units] * self.mlp_layers, activation="relu")(v)
            if detach_encoder_features:
                v = jax.lax.stop_gradient(v)
            v = nn.Dense(self.features_dim)(v)
            v = nn.LayerNorm()(v)
            feats.append(jnp.tanh(v))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]


class SACAEDecoder(nn.Module):
    """Inverse of the encoder (reference agent.py:122-201): fc back to the
    conv feature map, 3 stride-1 deconvs, one stride-2 deconv to 64x64."""

    cnn_keys: Sequence[str]
    cnn_channels: Sequence[int]
    mlp_keys: Sequence[str]
    mlp_dims: Sequence[int]
    features_dim: int = 64
    channels_multiplier: int = 1
    screen_size: int = 64
    dense_units: int = 64
    mlp_layers: int = 2

    @nn.compact
    def __call__(self, features: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_keys:
            conv_hw = (self.screen_size - 3) // 2 + 1 - 6  # 64 -> 31 -> 29 -> 27 -> 25
            ch = 32 * self.channels_multiplier
            lead = features.shape[:-1]
            x = nn.Dense(conv_hw * conv_hw * ch)(features)
            x = x.reshape((-1, conv_hw, conv_hw, ch))
            for _ in range(3):
                x = nn.ConvTranspose(ch, (3, 3), strides=(1, 1), padding="VALID")(x)
                x = jax.nn.relu(x)
            x = nn.ConvTranspose(int(sum(self.cnn_channels)), (4, 4), strides=(2, 2), padding="VALID")(x)
            x = jnp.transpose(x, (0, 3, 1, 2))
            x = x.reshape(lead + x.shape[1:])
            start = 0
            for k, c in zip(self.cnn_keys, self.cnn_channels):
                out[k] = x[..., start : start + c, :, :]
                start += c
        if self.mlp_keys:
            v = MLP(hidden_sizes=[self.dense_units] * self.mlp_layers, activation="relu")(features)
            start = 0
            v = nn.Dense(int(sum(self.mlp_dims)))(v)
            for k, d in zip(self.mlp_keys, self.mlp_dims):
                out[k] = v[..., start : start + d]
                start += d
        return out


class SACAEActor(nn.Module):
    """Tanh-Gaussian actor over encoder features (reference agent.py:240-318)."""

    action_dim: int
    hidden_size: int = 1024
    action_low: Sequence[float] | float = -1.0
    action_high: Sequence[float] | float = 1.0

    @nn.compact
    def __call__(self, features: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu")(features)
        mean = nn.Dense(self.action_dim)(x)
        log_std = nn.Dense(self.action_dim)(x)
        std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
        return mean, std

    def _scale(self):
        low = jnp.asarray(self.action_low, jnp.float32)
        high = jnp.asarray(self.action_high, jnp.float32)
        return (high - low) / 2.0, (high + low) / 2.0

    def sample_and_log_prob(self, features: jax.Array, key: jax.Array):
        mean, std = self(features)
        scale, bias = self._scale()
        x_t = mean + std * jax.random.normal(key, mean.shape)
        y_t = jnp.tanh(x_t)
        action = y_t * scale + bias
        var = std**2
        log_prob = -((x_t - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)
        log_prob = log_prob - jnp.log(scale * (1 - y_t**2) + 1e-6)
        return action, jnp.sum(log_prob, axis=-1, keepdims=True)

    def greedy_action(self, features: jax.Array) -> jax.Array:
        mean, _ = self(features)
        scale, bias = self._scale()
        return jnp.tanh(mean) * scale + bias


class _QNetwork(nn.Module):
    hidden_size: int = 1024

    @nn.compact
    def __call__(self, features: jax.Array, actions: jax.Array) -> jax.Array:
        x = jnp.concatenate([features, actions], axis=-1)
        return MLP(hidden_sizes=(self.hidden_size, self.hidden_size), output_dim=1, activation="relu")(x)


class SACAECritics(nn.Module):
    num_critics: int = 2
    hidden_size: int = 1024

    @nn.compact
    def __call__(self, features: jax.Array, actions: jax.Array) -> jax.Array:
        vmapped = nn.vmap(
            _QNetwork,
            in_axes=None,
            out_axes=-1,
            axis_size=self.num_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(hidden_size=self.hidden_size)
        return vmapped(features, actions)[..., 0, :]


def build_agent(
    runtime,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """Returns (encoder_def, decoder_def, actor_def, critic_def, params,
    target_entropy) — params holds encoder/decoder/actor/qfs plus the target
    encoder/qfs copies and log_alpha (reference agent.py:321-640)."""
    act_dim = int(prod(action_space.shape))
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    encoder_def = SACAEEncoder(
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        features_dim=cfg.algo.encoder.features_dim,
        channels_multiplier=cfg.algo.encoder.cnn_channels_multiplier,
        dense_units=cfg.algo.encoder.dense_units,
        mlp_layers=cfg.algo.encoder.mlp_layers,
    )
    decoder_def = SACAEDecoder(
        cnn_keys=tuple(cfg.algo.cnn_keys.decoder),
        cnn_channels=tuple(int(prod(obs_space[k].shape[:-2])) for k in cfg.algo.cnn_keys.decoder),
        mlp_keys=tuple(cfg.algo.mlp_keys.decoder),
        mlp_dims=tuple(int(prod(obs_space[k].shape)) for k in cfg.algo.mlp_keys.decoder),
        features_dim=cfg.algo.encoder.features_dim,
        channels_multiplier=cfg.algo.decoder.cnn_channels_multiplier,
        screen_size=cfg.env.screen_size,
        dense_units=cfg.algo.decoder.dense_units,
        mlp_layers=cfg.algo.decoder.mlp_layers,
    )
    actor_def = SACAEActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.hidden_size,
        action_low=tuple(np.asarray(action_space.low, np.float32).reshape(-1).tolist()),
        action_high=tuple(np.asarray(action_space.high, np.float32).reshape(-1).tolist()),
    )
    critic_def = SACAECritics(num_critics=cfg.algo.critic.n, hidden_size=cfg.algo.hidden_size)

    keys = jax.random.split(jax.random.PRNGKey(int(cfg.seed or 0)), 4)
    sample_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        sample_obs[k] = jnp.zeros((1,) + tuple(obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, int(prod(obs_space[k].shape))), jnp.float32)
    encoder_params = encoder_def.init(keys[0], sample_obs)
    feat_dim = cfg.algo.encoder.features_dim * ((1 if cnn_keys else 0) + (1 if mlp_keys else 0))
    dummy_feat = jnp.zeros((1, feat_dim), jnp.float32)
    decoder_params = decoder_def.init(keys[1], dummy_feat)
    actor_params = actor_def.init(keys[2], dummy_feat)
    critic_params = critic_def.init(keys[3], dummy_feat, jnp.zeros((1, act_dim), jnp.float32))
    params = {
        "encoder": encoder_params,
        "decoder": decoder_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_encoder": jax.tree_util.tree_map(jnp.copy, encoder_params),
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], jnp.float32)),
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    target_entropy = -act_dim
    return encoder_def, decoder_def, actor_def, critic_def, params, target_entropy
