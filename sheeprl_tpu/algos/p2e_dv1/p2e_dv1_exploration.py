"""Plan2Explore-DV1 exploration (reference
/root/reference/sheeprl/algos/p2e_dv1/p2e_dv1_exploration.py:40-801).

DreamerV1 world-model learning + ensemble learning (next *observation
embedding* prediction, reference :165-185) + exploration behaviour (dynamics
backprop on intrinsic lambda values, :186-265) + zero-shot task behaviour,
fused into one jitted train step.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import optax

from sheeprl_tpu.algos.dreamer_v1.agent import PlayerDV1
from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v1.utils import compute_lambda_values
from sheeprl_tpu.algos.dreamer_v2.loss import normal_log_prob
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _dreamer_main
from sheeprl_tpu.algos.dreamer_v3.utils import test
from sheeprl_tpu.algos.p2e_dv1.agent import build_agent
from sheeprl_tpu.algos.p2e_dv1.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER  # noqa: F401
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.ops.distributions import Bernoulli
from sheeprl_tpu.parallel.dp import P, batch_spec, dp_axis, dp_jit, fold_key, pmean_tree
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.registry import register_algorithm

_P2E = {"ensemble_def": None}

METRIC_ORDER = [
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "Loss/ensemble_loss",
    "Loss/policy_loss_exploration",
    "Loss/value_loss_exploration",
    "Loss/policy_loss_task",
    "Loss/value_loss_task",
    "Rewards/intrinsic",
    "Values_exploration/predicted_values",
    "Values_exploration/lambda_values",
    "Grads/world_model",
    "Grads/ensemble",
    "Grads/actor_exploration",
    "Grads/critic_exploration",
    "Grads/actor_task",
    "Grads/critic_task",
]


def make_train_step(
    world_model_def,
    actor_def,
    critic_def,
    optimizers,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    mesh=None,
):
    axis = dp_axis(mesh)
    cdt = compute_dtype_of(cfg)
    ensemble_def = _P2E["ensemble_def"]
    wm_cfg = cfg.algo.world_model
    stochastic_size = wm_cfg.stochastic_size
    recurrent_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    use_continues = wm_cfg.use_continues
    intrinsic_mult = cfg.algo.intrinsic_reward_multiplier

    def ensembles_apply(ens_params, x):
        return jax.vmap(lambda p: ensemble_def.apply(p, x))(ens_params)

    def imagine(wm_params, actor_params, posteriors, recurrents, k_img):
        """DV1 imagination: H imagined latents + the actions that produced
        them (reference :186-205)."""
        latent0 = jnp.concatenate([posteriors, recurrents], axis=-1)

        def img_body(carry, key_t):
            prior, recurrent, latent = carry
            k_act, k_dyn = jax.random.split(key_t)
            actions = actor_def.apply(actor_params, jax.lax.stop_gradient(latent), k_act, False, method="act")
            prior, recurrent = world_model_def.apply(
                wm_params, prior, recurrent, actions, k_dyn, method="imagination"
            )
            latent = jnp.concatenate([prior, recurrent], axis=-1)
            return (prior, recurrent, latent), (latent, actions)

        keys_h = jax.random.split(k_img, horizon)
        _, (latents_h, actions_h) = jax.lax.scan(img_body, (posteriors, recurrents, latent0), keys_h)
        return latents_h, actions_h  # [H, TB, ...]

    def train_step(params, opt_states, moments_state, batch, key, tau):
        del tau  # DV1 has no target critics
        T, B = batch["actions"].shape[:2]
        key = fold_key(key, axis)
        k_wm, k_img_e, k_img_t = jax.random.split(key, 3)

        target_obs = {k: batch[k] for k in set(cnn_dec_keys + mlp_dec_keys)}  # fp32 targets
        batch_obs = cast_floating(target_obs, cdt)
        batch_actions = cast_floating(batch["actions"], cdt)

        # ---------------- DYNAMIC LEARNING (as DV1) ------------------------
        def wm_loss_fn(wm_params):
            wm_params = cast_floating(wm_params, cdt)
            embedded = world_model_def.apply(wm_params, batch_obs, method="encode")

            def scan_body(carry, x):
                posterior, recurrent = carry
                action_t, embed_t, key_t = x
                recurrent, posterior, _, post_ms, prior_ms = world_model_def.apply(
                    wm_params, posterior, recurrent, action_t, embed_t, key_t, method="dynamic"
                )
                return (posterior, recurrent), (recurrent, posterior, post_ms, prior_ms)

            keys_t = jax.random.split(k_wm, T)
            init = (jnp.zeros((B, stochastic_size), cdt), jnp.zeros((B, recurrent_size), cdt))
            _, (recurrents, posteriors, post_ms, prior_ms) = jax.lax.scan(
                scan_body, init, (batch_actions, embedded, keys_t)
            )
            latents = jnp.concatenate([posteriors, recurrents], axis=-1)
            recon = world_model_def.apply(wm_params, latents, method="decode")
            reward_mean = world_model_def.apply(wm_params, latents, method="reward_logits")
            if use_continues:
                qc = Bernoulli(
                    world_model_def.apply(wm_params, latents, method="continue_logits"), event_dims=1
                )
                continues_targets = (1 - batch["terminated"]) * gamma
            else:
                qc = continues_targets = None
            rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                recon,
                target_obs,
                reward_mean,
                batch["rewards"],
                post_ms,
                prior_ms,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                qc,
                continues_targets,
                wm_cfg.continue_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrents": recurrents,
                "embedded": embedded,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
            }
            return rec_loss, aux

        (rec_loss, aux), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(params["world_model"])
        wm_grads = pmean_tree(wm_grads, axis)
        updates, opt_states["world_model"] = optimizers["world_model"].update(
            wm_grads, opt_states["world_model"], params["world_model"]
        )
        params["world_model"] = optax.apply_updates(params["world_model"], updates)
        wm_params = cast_floating(params["world_model"], cdt)

        posteriors = jax.lax.stop_gradient(aux["posteriors"])  # [T, B, S]
        recurrents = jax.lax.stop_gradient(aux["recurrents"])
        embedded = jax.lax.stop_gradient(aux["embedded"])  # [T, B, E]

        # ---------------- ENSEMBLE LEARNING (reference :165-185) -----------
        def ens_loss_fn(ens_params):
            inp = jnp.concatenate([posteriors, recurrents, batch_actions], axis=-1)
            outs = ensembles_apply(cast_floating(ens_params, cdt), inp)[:, :-1]  # [N, T-1, B, E]
            target = jnp.broadcast_to(embedded[1:][None], outs.shape)
            lp = normal_log_prob(outs, target, 1)
            return -jnp.mean(lp, axis=(1, 2)).sum()

        ens_loss, ens_grads = jax.value_and_grad(ens_loss_fn)(params["ensembles"])
        ens_grads = pmean_tree(ens_grads, axis)
        updates, opt_states["ensembles"] = optimizers["ensembles"].update(
            ens_grads, opt_states["ensembles"], params["ensembles"]
        )
        params["ensembles"] = optax.apply_updates(params["ensembles"], updates)

        flat_post = posteriors.reshape(T * B, stochastic_size)
        flat_rec = recurrents.reshape(T * B, recurrent_size)

        # ---------------- EXPLORATION BEHAVIOUR (reference :186-265) -------
        def actor_expl_loss_fn(actor_params):
            actor_params = cast_floating(actor_params, cdt)
            trajectories, actions = imagine(wm_params, actor_params, flat_post, flat_rec, k_img_e)
            values = critic_def.apply(
                cast_floating(params["critic_exploration"], cdt), trajectories
            ).astype(jnp.float32)

            ens_in = jax.lax.stop_gradient(jnp.concatenate([trajectories, actions], axis=-1))
            preds = ensembles_apply(cast_floating(params["ensembles"], cdt), ens_in).astype(
                jnp.float32
            )  # [N, H, TB, E]
            intrinsic_reward = (
                jnp.var(preds, axis=0, ddof=1).mean(-1, keepdims=True) * intrinsic_mult
            )
            if use_continues:
                continues = jax.nn.sigmoid(
                    world_model_def.apply(wm_params, trajectories, method="continue_logits")
                ).astype(jnp.float32)
            else:
                continues = jnp.ones_like(jax.lax.stop_gradient(intrinsic_reward)) * gamma

            lambda_values = compute_lambda_values(
                intrinsic_reward,
                values,
                continues,
                last_values=values[-1],
                horizon=horizon,
                lmbda=cfg.algo.lmbda,
            )
            discount = jax.lax.stop_gradient(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], axis=0), axis=0)
            )
            loss = -jnp.mean(discount * lambda_values)
            aux2 = {
                "trajectories": jax.lax.stop_gradient(trajectories),
                "lambda_values": jax.lax.stop_gradient(lambda_values),
                "discount": discount,
                "intrinsic_reward": jnp.mean(jax.lax.stop_gradient(intrinsic_reward)),
                "predicted_values": jnp.mean(jax.lax.stop_gradient(values)),
            }
            return loss, aux2

        (policy_loss_expl, aux_e), actor_expl_grads = jax.value_and_grad(actor_expl_loss_fn, has_aux=True)(
            params["actor_exploration"]
        )
        actor_expl_grads = pmean_tree(actor_expl_grads, axis)
        updates, opt_states["actor_exploration"] = optimizers["actor_exploration"].update(
            actor_expl_grads, opt_states["actor_exploration"], params["actor_exploration"]
        )
        params["actor_exploration"] = optax.apply_updates(params["actor_exploration"], updates)

        def critic_expl_loss_fn(critic_params):
            values = critic_def.apply(cast_floating(critic_params, cdt), aux_e["trajectories"])[:-1]
            lp = normal_log_prob(values, aux_e["lambda_values"], 1)
            return -jnp.mean(aux_e["discount"][..., 0] * lp)

        value_loss_expl, critic_expl_grads = jax.value_and_grad(critic_expl_loss_fn)(
            params["critic_exploration"]
        )
        critic_expl_grads = pmean_tree(critic_expl_grads, axis)
        updates, opt_states["critic_exploration"] = optimizers["critic_exploration"].update(
            critic_expl_grads, opt_states["critic_exploration"], params["critic_exploration"]
        )
        params["critic_exploration"] = optax.apply_updates(params["critic_exploration"], updates)

        # ---------------- TASK BEHAVIOUR (zero-shot, as DV1) ---------------
        def actor_task_loss_fn(actor_params):
            actor_params = cast_floating(actor_params, cdt)
            trajectories, _ = imagine(wm_params, actor_params, flat_post, flat_rec, k_img_t)
            values = critic_def.apply(cast_floating(params["critic_task"], cdt), trajectories).astype(
                jnp.float32
            )
            rewards = world_model_def.apply(wm_params, trajectories, method="reward_logits").astype(
                jnp.float32
            )
            if use_continues:
                continues = jax.nn.sigmoid(
                    world_model_def.apply(wm_params, trajectories, method="continue_logits")
                ).astype(jnp.float32)
            else:
                continues = jnp.ones_like(jax.lax.stop_gradient(rewards)) * gamma
            lambda_values = compute_lambda_values(
                rewards,
                values,
                continues,
                last_values=values[-1],
                horizon=horizon,
                lmbda=cfg.algo.lmbda,
            )
            discount = jax.lax.stop_gradient(
                jnp.cumprod(jnp.concatenate([jnp.ones_like(continues[:1]), continues[:-2]], axis=0), axis=0)
            )
            loss = -jnp.mean(discount * lambda_values)
            aux3 = {
                "trajectories": jax.lax.stop_gradient(trajectories),
                "lambda_values": jax.lax.stop_gradient(lambda_values),
                "discount": discount,
            }
            return loss, aux3

        (policy_loss_task, aux_t), actor_task_grads = jax.value_and_grad(actor_task_loss_fn, has_aux=True)(
            params["actor_task"]
        )
        actor_task_grads = pmean_tree(actor_task_grads, axis)
        updates, opt_states["actor_task"] = optimizers["actor_task"].update(
            actor_task_grads, opt_states["actor_task"], params["actor_task"]
        )
        params["actor_task"] = optax.apply_updates(params["actor_task"], updates)

        def critic_task_loss_fn(critic_params):
            values = critic_def.apply(cast_floating(critic_params, cdt), aux_t["trajectories"])[:-1]
            lp = normal_log_prob(values, aux_t["lambda_values"], 1)
            return -jnp.mean(aux_t["discount"][..., 0] * lp)

        value_loss_task, critic_task_grads = jax.value_and_grad(critic_task_loss_fn)(params["critic_task"])
        critic_task_grads = pmean_tree(critic_task_grads, axis)
        updates, opt_states["critic_task"] = optimizers["critic_task"].update(
            critic_task_grads, opt_states["critic_task"], params["critic_task"]
        )
        params["critic_task"] = optax.apply_updates(params["critic_task"], updates)

        metrics = jnp.stack(
            [
                rec_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                ens_loss,
                policy_loss_expl,
                value_loss_expl,
                policy_loss_task,
                value_loss_task,
                aux_e["intrinsic_reward"],
                aux_e["predicted_values"],
                jnp.mean(aux_e["lambda_values"]),
                optax.global_norm(wm_grads),
                optax.global_norm(ens_grads),
                optax.global_norm(actor_expl_grads),
                optax.global_norm(critic_expl_grads),
                optax.global_norm(actor_task_grads),
                optax.global_norm(critic_task_grads),
            ]
        )
        metrics = pmean_tree(metrics, axis)
        return params, opt_states, moments_state, metrics

    from sheeprl_tpu.parallel.dp import fsdp_min_shard_bytes

    return dp_jit(
        train_step,
        mesh,
        in_specs=(P(), P(), P(), batch_spec(batch_axis=1), P(), P()),
        out_specs=(P(), P(), P(), P()),
        donate_argnums=(0, 1, 2),
        min_shard_bytes=fsdp_min_shard_bytes(cfg),
    )


def _build_agent(runtime, actions_dim, is_continuous, cfg, obs_space, state):
    world_model_def, actor_def, critic_def, ensemble_def, params = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        state["world_model"] if state else None,
        state["ensembles"] if state else None,
        state["actor_task"] if state else None,
        state["critic_task"] if state else None,
        state["actor_exploration"] if state else None,
        state["critic_exploration"] if state else None,
    )
    _P2E["ensemble_def"] = ensemble_def
    return world_model_def, actor_def, critic_def, params


def _make_optimizers(cfg, params, agent_state):
    chain = lambda clip, opt_cfg: optax.chain(  # noqa: E731
        optax.clip_by_global_norm(clip), instantiate(opt_cfg)
    )
    optimizers = {
        "world_model": chain(cfg.algo.world_model.clip_gradients, cfg.algo.world_model.optimizer),
        "actor_task": chain(cfg.algo.actor.clip_gradients, cfg.algo.actor.optimizer),
        "critic_task": chain(cfg.algo.critic.clip_gradients, cfg.algo.critic.optimizer),
        "actor_exploration": chain(cfg.algo.actor.clip_gradients, cfg.algo.actor.optimizer),
        "critic_exploration": chain(cfg.algo.critic.clip_gradients, cfg.algo.critic.optimizer),
        "ensembles": chain(cfg.algo.ensembles.clip_gradients, cfg.algo.ensembles.optimizer),
    }
    opt_states = {k: opt.init(params[k]) for k, opt in optimizers.items()}
    if agent_state and "opt_states" in agent_state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            agent_state["opt_states"],
        )
    return optimizers, opt_states


def _player_actor(cfg):
    actor_type = cfg.algo.player.actor_type

    def fn(params, has_trained):
        return params["actor_exploration"] if actor_type == "exploration" else params["actor_task"]

    return fn


def _zero_shot_test(player, params, runtime, cfg, log_dir):
    return test(
        player, params["world_model"], params["actor_task"], runtime, cfg, log_dir, "zero-shot", greedy=False
    )


@register_algorithm()
def main(runtime, cfg):
    cfg.algo.player.actor_type = "exploration"
    return _dreamer_main(
        runtime,
        cfg,
        _build_agent,
        make_train_step,
        make_optimizers_fn=_make_optimizers,
        init_moments_fn=lambda cfg, agent_state: {},
        player_actor_fn=_player_actor(cfg),
        metric_order=METRIC_ORDER,
        final_test_fn=_zero_shot_test,
        player_cls=PlayerDV1,
    )
