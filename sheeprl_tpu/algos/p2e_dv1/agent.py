"""Plan2Explore-DV1 agent (reference /root/reference/sheeprl/algos/p2e_dv1/agent.py:27-155).

DreamerV1 stack + exploration actor, a single exploration critic (DV1 has no
target critics), and a vmapped ensemble predicting the **embedded
observation** at t+1 from ``(posterior, recurrent, action)``
(reference agent.py:125-145, output dim = encoder output size)."""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v1.agent import build_agent as dv1_build_agent
from sheeprl_tpu.algos.p2e_dv3.agent import Ensemble


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    world_model_state: Optional[Dict[str, Any]] = None,
    ensembles_state: Optional[Dict[str, Any]] = None,
    actor_task_state: Optional[Dict[str, Any]] = None,
    critic_task_state: Optional[Dict[str, Any]] = None,
    actor_exploration_state: Optional[Dict[str, Any]] = None,
    critic_exploration_state: Optional[Dict[str, Any]] = None,
):
    """Returns ``(world_model_def, actor_def, critic_def, ensemble_def,
    params)`` with params keys: world_model, actor_task, critic_task,
    actor_exploration, critic_exploration, ensembles."""
    world_model_def, actor_def, critic_def, dv1_params = dv1_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
    )
    wm_cfg = cfg.algo.world_model
    latent_state_size = wm_cfg.stochastic_size + wm_cfg.recurrent_model.recurrent_state_size

    key = jax.random.PRNGKey(int(cfg.seed or 0) + 41)
    k_actor, k_critic, k_ens = jax.random.split(key, 3)
    sample_latent = jnp.zeros((1, latent_state_size), jnp.float32)

    actor_exploration_params = actor_def.init(k_actor, sample_latent)
    if actor_exploration_state is not None:
        actor_exploration_params = jax.tree_util.tree_map(jnp.asarray, actor_exploration_state)
    critic_exploration_params = critic_def.init(k_critic, sample_latent)
    if critic_exploration_state is not None:
        critic_exploration_params = jax.tree_util.tree_map(jnp.asarray, critic_exploration_state)

    # probe the encoder output dim — the ensemble target (reference
    # agent.py:136: cnn_output_dim + mlp_output_dim)
    sample_obs: Dict[str, jax.Array] = {}
    for k in cfg.algo.cnn_keys.encoder:
        sample_obs[k] = jnp.zeros((1,) + tuple(obs_space[k].shape), jnp.float32)
    for k in cfg.algo.mlp_keys.encoder:
        sample_obs[k] = jnp.zeros((1, int(prod(obs_space[k].shape))), jnp.float32)
    embedded = world_model_def.apply(dv1_params["world_model"], sample_obs, method="encode")
    embedding_size = int(embedded.shape[-1])

    ens_cfg = cfg.algo.ensembles
    ensemble_def = Ensemble(
        output_dim=embedding_size,
        dense_units=ens_cfg.dense_units,
        mlp_layers=ens_cfg.mlp_layers,
        layer_norm=False,
        hafner_initialization=False,
        act=cfg.algo.dense_act,
    )
    sample_in = jnp.zeros((1, latent_state_size + int(sum(actions_dim))), jnp.float32)
    member_keys = jax.random.split(k_ens, int(ens_cfg.n))
    ensembles_params = jax.vmap(lambda k: ensemble_def.init(k, sample_in))(member_keys)
    if ensembles_state is not None:
        ensembles_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)

    params = {
        "world_model": dv1_params["world_model"],
        "actor_task": dv1_params["actor"],
        "critic_task": dv1_params["critic"],
        "actor_exploration": actor_exploration_params,
        "critic_exploration": critic_exploration_params,
        "ensembles": ensembles_params,
    }
    return world_model_def, actor_def, critic_def, ensemble_def, params
