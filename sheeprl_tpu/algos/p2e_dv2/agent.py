"""Plan2Explore-DV2 agent (reference /root/reference/sheeprl/algos/p2e_dv2/agent.py:30-209).

DreamerV2 stack + exploration actor, a single exploration critic with target
copy, and a vmapped ensemble predicting the next stochastic state from
``(posterior, recurrent, action)`` (reference agent.py:120-165)."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.agent import build_agent as dv2_build_agent
from sheeprl_tpu.algos.p2e_dv3.agent import Ensemble


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    world_model_state: Optional[Dict[str, Any]] = None,
    ensembles_state: Optional[Dict[str, Any]] = None,
    actor_task_state: Optional[Dict[str, Any]] = None,
    critic_task_state: Optional[Dict[str, Any]] = None,
    target_critic_task_state: Optional[Dict[str, Any]] = None,
    actor_exploration_state: Optional[Dict[str, Any]] = None,
    critic_exploration_state: Optional[Dict[str, Any]] = None,
    target_critic_exploration_state: Optional[Dict[str, Any]] = None,
):
    """Returns ``(world_model_def, actor_def, critic_def, ensemble_def,
    params)`` with params keys: world_model, actor_task, critic_task,
    target_critic_task, actor_exploration, critic_exploration,
    target_critic_exploration, ensembles."""
    world_model_def, actor_def, critic_def, dv2_params = dv2_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_task_state,
        critic_task_state,
        target_critic_task_state,
    )
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size
    latent_state_size = stoch_flat + wm_cfg.recurrent_model.recurrent_state_size

    key = jax.random.PRNGKey(int(cfg.seed or 0) + 29)
    k_actor, k_critic, k_ens = jax.random.split(key, 3)
    sample_latent = jnp.zeros((1, latent_state_size), jnp.float32)

    actor_exploration_params = actor_def.init(k_actor, sample_latent)
    if actor_exploration_state is not None:
        actor_exploration_params = jax.tree_util.tree_map(jnp.asarray, actor_exploration_state)
    critic_exploration_params = critic_def.init(k_critic, sample_latent)
    if critic_exploration_state is not None:
        critic_exploration_params = jax.tree_util.tree_map(jnp.asarray, critic_exploration_state)
    target_critic_exploration_params = jax.tree_util.tree_map(jnp.copy, critic_exploration_params)
    if target_critic_exploration_state is not None:
        target_critic_exploration_params = jax.tree_util.tree_map(
            jnp.asarray, target_critic_exploration_state
        )

    ens_cfg = cfg.algo.ensembles
    ensemble_def = Ensemble(
        output_dim=stoch_flat,
        dense_units=ens_cfg.dense_units,
        mlp_layers=ens_cfg.mlp_layers,
        layer_norm=bool(cfg.algo.get("layer_norm", False)),
        hafner_initialization=False,
    )
    sample_in = jnp.zeros((1, latent_state_size + int(sum(actions_dim))), jnp.float32)
    member_keys = jax.random.split(k_ens, int(ens_cfg.n))
    ensembles_params = jax.vmap(lambda k: ensemble_def.init(k, sample_in))(member_keys)
    if ensembles_state is not None:
        ensembles_params = jax.tree_util.tree_map(jnp.asarray, ensembles_state)

    params = {
        "world_model": dv2_params["world_model"],
        "actor_task": dv2_params["actor"],
        "critic_task": dv2_params["critic"],
        "target_critic_task": dv2_params["target_critic"],
        "actor_exploration": actor_exploration_params,
        "critic_exploration": critic_exploration_params,
        "target_critic_exploration": target_critic_exploration_params,
        "ensembles": ensembles_params,
    }
    return world_model_def, actor_def, critic_def, ensemble_def, params
