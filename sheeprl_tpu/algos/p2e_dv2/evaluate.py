"""P2E-DV2 evaluation (reference /root/reference/sheeprl/algos/p2e_dv2/evaluate.py):
evaluates the task actor from an exploration or finetuning checkpoint."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.dreamer_v2.agent import PlayerDV2
from sheeprl_tpu.algos.dreamer_v3.utils import test
from sheeprl_tpu.algos.p2e_dv2.agent import build_agent
from sheeprl_tpu.envs.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms=["p2e_dv2_exploration", "p2e_dv2_finetuning"])
def evaluate_p2e_dv2(runtime, cfg, state: Dict[str, Any]) -> None:
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    action_space = env.action_space
    observation_space = env.observation_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    is_finetune_ckpt = "actor" in state
    world_model_def, actor_def, _, _, params = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"],
        state.get("ensembles"),
        state["actor"] if is_finetune_ckpt else state["actor_task"],
        state["critic"] if is_finetune_ckpt else state["critic_task"],
        state["target_critic"] if is_finetune_ckpt else state["target_critic_task"],
        state.get("actor_exploration"),
        state.get("critic_exploration"),
        state.get("target_critic_exploration"),
    )
    player = PlayerDV2(world_model_def, actor_def, actions_dim, 1)
    env.close()
    cumulative_rew = test(
        player, params["world_model"], params["actor_task"], runtime, cfg, log_dir, greedy=False
    )
    logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    logger.finalize()
