"""Plan2Explore-DV2 finetuning (reference
/root/reference/sheeprl/algos/p2e_dv2/p2e_dv2_finetuning.py:28-469): loads an
exploration checkpoint and continues with the standard DreamerV2 train step;
the player switches exploration -> task actor at the first gradient step."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.agent import PlayerDV2
from sheeprl_tpu.algos.dreamer_v2.dreamer_v2 import METRIC_ORDER, make_train_step as dv2_make_train_step
from sheeprl_tpu.algos.dreamer_v2.utils import AGGREGATOR_KEYS  # noqa: F401
from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _default_make_optimizers, _dreamer_main
from sheeprl_tpu.algos.p2e_dv2.agent import build_agent
from sheeprl_tpu.algos.p2e_dv3.p2e_dv3_finetuning import (
    apply_exploration_cfg,
    load_exploration_cfg,
)
from sheeprl_tpu.utils.registry import register_algorithm

MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def make_train_step(*args, **kwargs):
    """Adapt the DV2 step (no Moments) to the engine's
    ``(params, opt_states, moments, batch, key, tau)`` signature."""
    dv2_step = dv2_make_train_step(*args, **kwargs)

    def step(params, opt_states, moments_state, batch, key, tau):
        params, opt_states, metrics = dv2_step(params, opt_states, batch, key, tau)
        return params, opt_states, moments_state, metrics

    return step


def _build_agent(runtime, actions_dim, is_continuous, cfg, obs_space, state):
    is_finetune_ckpt = state is not None and "actor" in state
    world_model_def, actor_def, critic_def, _, p2e_params = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        state["world_model"] if state else None,
        None,
        (state["actor"] if is_finetune_ckpt else state["actor_task"]) if state else None,
        (state["critic"] if is_finetune_ckpt else state["critic_task"]) if state else None,
        ((state["target_critic"] if is_finetune_ckpt else state["target_critic_task"]) if state else None),
        state["actor_exploration"] if state else None,
        None,
        None,
    )
    params = {
        "world_model": p2e_params["world_model"],
        "actor": p2e_params["actor_task"],
        "critic": p2e_params["critic_task"],
        "target_critic": p2e_params["target_critic_task"],
        "actor_exploration": p2e_params["actor_exploration"],
    }
    return world_model_def, actor_def, critic_def, params


def _make_optimizers(cfg, params, agent_state):
    optimizers, opt_states = _default_make_optimizers(cfg, params, None)
    if agent_state and "opt_states" in agent_state:
        saved = agent_state["opt_states"]
        mapped = {
            "world_model": saved["world_model"],
            "actor": saved["actor_task"] if "actor_task" in saved else saved["actor"],
            "critic": saved["critic_task"] if "critic_task" in saved else saved["critic"],
        }
        opt_states = jax.tree_util.tree_map(
            lambda ref, s: jnp.asarray(s, dtype=getattr(ref, "dtype", None)), opt_states, mapped
        )
    return optimizers, opt_states


def _player_actor(cfg):
    def fn(params, has_trained):
        if has_trained or cfg.algo.player.actor_type == "task":
            return params["actor"]
        return params["actor_exploration"]

    return fn


@register_algorithm()
def main(runtime, cfg):
    exploration_cfg = load_exploration_cfg(cfg)
    apply_exploration_cfg(cfg, exploration_cfg)

    def load_agent_state_fn(runtime, cfg):
        return runtime.load(cfg.checkpoint.exploration_ckpt_path)

    return _dreamer_main(
        runtime,
        cfg,
        _build_agent,
        make_train_step,
        make_optimizers_fn=_make_optimizers,
        init_moments_fn=lambda cfg, agent_state: {},
        player_actor_fn=_player_actor(cfg),
        metric_order=METRIC_ORDER,
        load_agent_state_fn=load_agent_state_fn,
        player_cls=PlayerDV2,
    )
