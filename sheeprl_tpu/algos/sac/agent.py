"""SAC agent — TPU-native re-design of
/root/reference/sheeprl/algos/sac/agent.py:16-371.

- ``SACActor``: squashed diagonal Gaussian with clamped log-std and
  action-space rescaling (reference agent.py:57-142).
- ``SACCritics``: the twin/ensemble Q network as **one vmapped module** — the
  reference holds N separate MLPs in a ModuleList (agent.py:20-54,145-180);
  stacking them into a leading ensemble axis turns N small matmuls into one
  batched MXU matmul per layer.
- ``log_alpha`` automatic entropy tuning lives as its own 1-element params
  tree; the Polyak-averaged target critic is a second params pytree updated
  with ``optax.incremental_update`` (reference ``qfs_target_ema``,
  agent.py:204-233).
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.models.blocks import MLP

LOG_STD_MAX = 2.0
LOG_STD_MIN = -5.0


class SACActor(nn.Module):
    """Tanh-Gaussian actor (reference agent.py:57-142)."""

    action_dim: int
    hidden_size: int = 256
    action_low: Sequence[float] | float = -1.0
    action_high: Sequence[float] | float = 1.0

    @nn.compact
    def __call__(self, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), activation="relu")(obs)
        mean = nn.Dense(self.action_dim)(x)
        log_std = nn.Dense(self.action_dim)(x)
        std = jnp.exp(jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX))
        return mean, std

    def _scale(self) -> Tuple[jax.Array, jax.Array]:
        low = jnp.asarray(self.action_low, jnp.float32)
        high = jnp.asarray(self.action_high, jnp.float32)
        return (high - low) / 2.0, (high + low) / 2.0

    def sample_and_log_prob(self, obs: jax.Array, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """rsample + tanh change-of-variables log-prob (reference agent.py:109-142)."""
        mean, std = self(obs)
        scale, bias = self._scale()
        eps = jax.random.normal(key, mean.shape)
        x_t = mean + std * eps
        y_t = jnp.tanh(x_t)
        action = y_t * scale + bias
        var = std**2
        log_prob = -((x_t - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)
        log_prob = log_prob - jnp.log(scale * (1 - y_t**2) + 1e-6)
        return action, jnp.sum(log_prob, axis=-1, keepdims=True)

    def greedy_action(self, obs: jax.Array) -> jax.Array:
        mean, _ = self(obs)
        scale, bias = self._scale()
        return jnp.tanh(mean) * scale + bias


class _QNetwork(nn.Module):
    hidden_size: int = 256

    @nn.compact
    def __call__(self, obs: jax.Array, actions: jax.Array) -> jax.Array:
        x = jnp.concatenate([obs, actions], axis=-1)
        x = MLP(hidden_sizes=(self.hidden_size, self.hidden_size), output_dim=1, activation="relu")(x)
        return x


class SACCritics(nn.Module):
    """N Q-networks as one vmapped ensemble; output ``[..., N]``."""

    num_critics: int = 2
    hidden_size: int = 256

    @nn.compact
    def __call__(self, obs: jax.Array, actions: jax.Array) -> jax.Array:
        vmapped = nn.vmap(
            _QNetwork,
            in_axes=None,
            out_axes=-1,
            axis_size=self.num_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )(hidden_size=self.hidden_size)
        return vmapped(obs, actions)[..., 0, :]


def build_agent(
    runtime,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """Create actor/critic modules + params trees (reference agent.py:236-371).

    Returns ``(actor_def, critic_def, params)`` where params holds
    ``{"actor", "critic", "target_critic", "log_alpha"}``.
    """
    act_dim = int(prod(action_space.shape))
    obs_dim = int(sum(prod(obs_space[k].shape) for k in cfg.algo.mlp_keys.encoder))
    actor_def = SACActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        action_low=tuple(np.asarray(action_space.low, dtype=np.float32).reshape(-1).tolist()),
        action_high=tuple(np.asarray(action_space.high, dtype=np.float32).reshape(-1).tolist()),
    )
    critic_def = SACCritics(num_critics=cfg.algo.critic.n, hidden_size=cfg.algo.critic.hidden_size)
    k1, k2 = jax.random.split(jax.random.PRNGKey(int(cfg.seed or 0)))
    dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)
    actor_params = actor_def.init(k1, dummy_obs)
    critic_params = critic_def.init(k2, dummy_obs, dummy_act)
    params = {
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], jnp.float32)),
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    target_entropy = -act_dim  # reference sac.py:155: -prod(action shape)
    return actor_def, critic_def, params, target_entropy
