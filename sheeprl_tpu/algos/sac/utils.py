"""SAC helper surface (reference /root/reference/sheeprl/algos/sac/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
    "Grads/global_norm",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    obs: Dict[str, np.ndarray], *, mlp_keys: Sequence[str] = (), num_envs: int = 1, sharding: Any = None
) -> jax.Array:
    """Concatenate vector keys into the flat observation the SAC nets consume
    (reference utils.py:13-24) — one staged h2d for the whole slab; pass a
    reused ``sharding`` (``envs/player.py::obs_sharding``) from hot loops."""
    arr = np.concatenate([np.asarray(obs[k], dtype=np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1)
    return jnp.asarray(arr) if sharding is None else jax.device_put(arr, sharding)


def test(actor_apply, actor_params, env, runtime, cfg, log_dir: str) -> float:
    """One greedy episode (reference utils.py:27-51)."""
    done = False
    cumulative_rew = 0.0
    obs, _ = env.reset(seed=cfg.seed)
    while not done:
        flat_obs = prepare_obs(obs, mlp_keys=cfg.algo.mlp_keys.encoder)
        action = actor_apply(actor_params, flat_obs, method="greedy_action")
        obs, reward, terminated, truncated, _ = env.step(np.asarray(action).reshape(env.action_space.shape))
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    env.close()
    return cumulative_rew
