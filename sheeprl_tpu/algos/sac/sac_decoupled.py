"""Decoupled SAC — TPU-native re-design of
/root/reference/sheeprl/algos/sac/sac_decoupled.py:33-588.

Same topology translation as decoupled PPO (ppo_decoupled.py): device 0 is
the buffer-resident player, devices 1..N-1 the trainer mesh.  The reference
scatters sampled batch data from the player to the trainer DDP group
(sac_decoupled.py:294-320) and broadcasts flat parameters back; here the
sampled replay batches are ``device_put`` sharded over the trainer sub-mesh
and the actor params hop back to the player device each iteration.
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.sac import make_train_step
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.envs.player import fetch_values
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, get_diagnostics, save_configs


@register_algorithm(decoupled=True)
def main(runtime, cfg):
    world_size = runtime.world_size
    if world_size < 2:
        raise RuntimeError(
            "Decoupled SAC needs at least 2 devices: 1 player + >=1 trainer "
            f"(got fabric.devices={world_size})"
        )
    player_device = runtime.devices[0]
    trainer_devices = runtime.devices[1:]
    trainer_mesh = Mesh(np.asarray(trainer_devices), ("data",))
    n_trainers = len(trainer_devices)
    num_envs = cfg.env.num_envs

    if cfg.algo.cnn_keys.encoder:
        import warnings

        warnings.warn("SAC only uses vector observations; CNN keys are ignored")

    rng_key = runtime.seed_everything(cfg.seed)
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("SAC supports only continuous (Box) action spaces")
    mlp_keys = cfg.algo.mlp_keys.encoder

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    actor_def, critic_def, params, target_entropy = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)
    optimizers = {
        "actor": instantiate(cfg.algo.actor.optimizer),
        "critic": instantiate(cfg.algo.critic.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
    }
    opt_states = {
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
        "alpha": optimizers["alpha"].init(params["log_alpha"]),
    }
    if state and "opt_states" in state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            state["opt_states"],
        )

    trainer_repl = NamedSharding(trainer_mesh, P())
    trainer_data_sharding = NamedSharding(trainer_mesh, P(None, "data"))
    params = jax.device_put(params, trainer_repl)
    opt_states = jax.device_put(opt_states, trainer_repl)
    player_actor_params = jax.device_put(
        jax.tree_util.tree_map(np.asarray, params["actor"]), player_device
    )

    train_step = diag.instrument(
        "train_step",
        make_train_step(actor_def, critic_def, optimizers, cfg, trainer_mesh, target_entropy),
        kind="train",
        donate_argnums=(0, 1),  # params, opt_states — audited at first dispatch
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)
    diag.register_footprint("player_params", player_actor_params)

    @jax.jit
    def _policy_step(actor_params, obs, key):
        actions, _ = actor_def.apply(actor_params, obs, key, method="sample_and_log_prob")
        return actions

    _policy_step = diag.instrument("policy_step", _policy_step, kind="rollout")
    # one staged h2d straight onto the player device per vector step
    stage_sharding = jax.sharding.SingleDeviceSharding(player_device)

    def policy_step(actor_params, obs, key):
        return _policy_step(actor_params, jax.device_put(obs, player_device), key)

    rb = ReplayBuffer(
        cfg.buffer.size,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=("observations",),
    )
    diag.track_buffer("replay", rb)
    if state and "rb" in state and state["rb"] is not None:
        rb.load_state_dict(state["rb"])

    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if cfg.checkpoint.resume_from:
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    batch_size = cfg.algo.per_rank_batch_size
    obs, _ = envs.reset(seed=cfg.seed)

    def restore_last_good(restored) -> None:
        """Re-materialize the trainer's params + opt state from the last-good
        host snapshot (the dispatch may have consumed the donated buffers)."""
        nonlocal params, opt_states
        params = jax.device_put(restored["params"], trainer_repl)
        opt_states = jax.device_put(restored["opt_state"], trainer_repl)

    def run_train(iter_num: int, per_rank_gradient_steps: int) -> None:
        """Sample + dispatch this iteration's gradient steps on the trainer
        sub-mesh and fetch the metrics (the blocking fetch included, so the
        whole thing rides inside the env-step overlap window)."""
        nonlocal rng_key, params, opt_states, player_actor_params
        with timer("Time/train_time"):
            # player samples; batches "scattered" onto the trainer mesh
            with diag.span("buffer-sample"):
                sample = rb.sample(
                    batch_size=batch_size * n_trainers,
                    n_samples=per_rank_gradient_steps,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )
                data = {
                    k: jax.device_put(jnp.asarray(np.asarray(v), jnp.float32), trainer_data_sharding)
                    for k, v in sample.items()
                    if k in ("observations", "next_observations", "actions", "rewards", "terminated")
                }
            data = diag.maybe_inject_nan(iter_num, data)
            # quarantined — the TRAIN DISPATCH only, like ppo_decoupled: a
            # sampling/staging failure is not a train-step incident and must
            # not burn the rollback budget (resilience.isolation.retry_budget)
            try:
                with diag.span("train", role="trainer"):
                    diag.maybe_chaos_trainer_fault(iter_num)
                    rng_key, scan_key = jax.random.split(rng_key)
                    keys = jax.random.split(scan_key, per_rank_gradient_steps)
                    params, opt_states, losses, health = train_step(params, opt_states, data, keys)
                    # one blocking d2h for metrics + health stats together
                    losses, health_host = fetch_values(losses, health)
            except Exception as err:
                restored = diag.quarantine(err, iter_num, policy_step_count)
                if restored is None:
                    raise
                restore_last_good(restored)
                return
        # last-good fencing: the actor-params hop to the player only happens
        # when the update judges healthy; a rejected update leaves the player
        # acting on its last-good actor params (reference :550-554)
        if diag.gate_promotion(
            iter_num, policy_step_count, stats=health_host, nonfinite=float(losses[4])
        ):
            player_actor_params = jax.device_put(params["actor"], player_device)
            diag.refresh_last_good(iter_num, params, opt_states)
        diag.on_health(policy_step_count, health_host)
        aggregator.update("Loss/value_loss", float(losses[0]))
        aggregator.update("Loss/policy_loss", float(losses[1]))
        aggregator.update("Loss/alpha_loss", float(losses[2]))
        aggregator.update("Grads/global_norm", float(losses[3]))
        try:
            diag.on_update(
                policy_step_count,
                {
                    "Loss/value_loss": float(losses[0]),
                    "Loss/policy_loss": float(losses[1]),
                    "Loss/alpha_loss": float(losses[2]),
                    "Grads/global_norm": float(losses[3]),
                },
                nonfinite=float(losses[4]),
            )
        except Exception as err:
            # sentinel policy=halt on a fenced update: roll the trainer back
            # and keep the run alive (the gate above already held the bad
            # params away from the player)
            restored = diag.quarantine(err, iter_num, policy_step_count)
            if restored is None:
                raise
            restore_last_good(restored)

    for iter_num in range(start_iter, total_iters + 1):
        policy_step_count += policy_steps_per_iter
        diag.note_env_steps(num_envs)
        with timer("Time/env_interaction_time"), diag.span("rollout", role="player"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                rng_key, step_key = jax.random.split(rng_key)
                flat_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs, sharding=stage_sharding)
                actions = np.asarray(policy_step(player_actor_params, flat_obs, step_key))
            with diag.span("env_step_async"):
                envs.step_async(actions.reshape(envs.action_space.shape))

        # --- two-stage pipeline: trainer-mesh gradient steps overlap the env
        # workers (same bounded one-transition sample lag as sac.py; empty
        # buffer falls back to the serialized order below) -------------------
        per_rank_gradient_steps = 0
        trained = False
        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step_count - prefill_steps * policy_steps_per_iter)
            if cfg.dry_run:
                per_rank_gradient_steps = 1
            if per_rank_gradient_steps > 0 and not rb.empty:
                run_train(iter_num, per_rank_gradient_steps)
                trained = True

        with timer("Time/env_interaction_time"), diag.span("env_wait"):
            next_obs, rewards, terminated, truncated, info = envs.step_wait()
        rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, -1)

        if "final_info" in info and "episode" in info["final_info"]:
            ep = info["final_info"]["episode"]
            mask = ep.get("_r", info["final_info"].get("_episode"))
            if mask is not None and np.any(mask):
                for r, l in zip(ep["r"][mask], ep["l"][mask]):
                    aggregator.update("Rewards/rew_avg", float(r))
                    aggregator.update("Game/ep_len_avg", float(l))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
        if "final_obs" in info:
            for idx, final_obs in enumerate(info["final_obs"]):
                if final_obs is not None:
                    for k in mlp_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs[k])

        flat = {
            "observations": np.concatenate(
                [np.asarray(obs[k], np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
            ),
            "actions": actions.reshape(num_envs, -1),
            "rewards": rewards,
            "terminated": terminated,
            "truncated": truncated,
        }
        if not cfg.buffer.sample_next_obs:
            flat["next_observations"] = np.concatenate(
                [real_next_obs[k].astype(np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
            )
        step_data: Dict[str, np.ndarray] = step_slab(
            num_envs, flat, dtypes={"terminated": np.float32, "truncated": np.float32}
        )
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        obs = next_obs

        # --- train fallback: pipelined site skipped on an empty buffer -----
        if per_rank_gradient_steps > 0 and not trained:
            run_train(iter_num, per_rank_gradient_steps)

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # a pending preemption (signal or drill) or an exhausted staleness
        # budget forces the branch: the save below IS the emergency snapshot
        # (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        fence_halt_now = diag.fence_halt_due()
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or fence_halt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            agent_save = jax.tree_util.tree_map(np.asarray, params)
            opt_save = jax.tree_util.tree_map(np.asarray, opt_states)
            ckpt_iter, ckpt_step = iter_num, policy_step_count
            if fence_halt_now:
                # the fence escalated BECAUSE the live trainer state is bad:
                # the emergency snapshot must be the last-good state, not the
                # corruption it is escaping — with the counters (and the
                # file/manifest step) of the iteration it came FROM
                last_good = diag.last_good_state()
                if last_good is not None:
                    agent_save, opt_save = last_good["params"], last_good["opt_state"]
                    ckpt_iter = last_good["iter_num"]
                    ckpt_step = ckpt_iter * policy_steps_per_iter
            ckpt_state = {
                "agent": agent_save,
                "opt_states": opt_save,
                "ratio": ratio.state_dict(),
                "iter_num": ckpt_iter,
                "policy_step": ckpt_step,
                "last_log": min(last_log, ckpt_step),
                "last_checkpoint": min(last_checkpoint, ckpt_step),
                "batch_size": batch_size * n_trainers,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{ckpt_step}_0.ckpt")
            with diag.span("checkpoint"):
                runtime.call(
                    "on_checkpoint_player",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            diag.on_checkpoint(policy_step_count, ckpt_path)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)
            if fence_halt_now:
                envs.close()
                diag.on_fence_halt(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(actor_def.apply, player_actor_params, test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    logger.finalize()
    diag.close("completed")
