"""SAC losses (reference /root/reference/sheeprl/algos/sac/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def critic_loss(qf_values: jax.Array, next_qf_value: jax.Array, num_critics: int) -> jax.Array:
    """Sum of per-critic MSE against the shared soft target
    (reference loss.py:9-18)."""
    del num_critics  # derived from the trailing axis
    return jnp.sum(jnp.mean((qf_values - next_qf_value) ** 2, axis=tuple(range(qf_values.ndim - 1))))


def policy_loss(alpha: jax.Array, logprobs: jax.Array, min_qf_values: jax.Array) -> jax.Array:
    """alpha*logpi - minQ (reference loss.py:21-24)."""
    return jnp.mean(alpha * logprobs - min_qf_values)


def entropy_loss(log_alpha: jax.Array, logprobs: jax.Array, target_entropy: float) -> jax.Array:
    """Automatic entropy-coefficient loss (reference loss.py:27-30)."""
    return jnp.mean(-log_alpha * (jax.lax.stop_gradient(logprobs) + target_entropy))


def conservative_q_penalty(
    key: jax.Array,
    obs_c: jax.Array,
    qf_values: jax.Array,
    actor_apply,
    critic_apply,
    act_low,
    act_high,
    n_samples: int,
) -> jax.Array:
    """Simplified CQL(H) term shared by the SAC and DroQ offline-mode critic
    losses (howto/offline_rl.md): logsumexp of Q over ``n_samples`` uniform +
    ``n_samples`` fresh policy action proposals minus the dataset Q — pushes
    Q down on out-of-distribution actions, up on the data's.

    ``actor_apply(obs, key) -> (actions, logprobs)`` and
    ``critic_apply(obs, actions) -> q`` close over their (already
    compute-dtype-cast) params; ``qf_values`` is the fp32 dataset Q the
    caller already computed, so no reduction is duplicated.
    """
    k_unif, k_pol = jax.random.split(key)
    rand_actions = jax.random.uniform(
        k_unif,
        (int(n_samples), obs_c.shape[0], jnp.asarray(act_low).shape[0]),
        minval=jnp.asarray(act_low),
        maxval=jnp.asarray(act_high),
        dtype=jnp.float32,
    )
    pol_actions, _ = jax.vmap(lambda k: actor_apply(obs_c, k))(
        jax.random.split(k_pol, int(n_samples))
    )
    proposals = jnp.concatenate(
        [rand_actions.astype(obs_c.dtype), jax.lax.stop_gradient(pol_actions)], axis=0
    )
    q_prop = jax.vmap(lambda a: critic_apply(obs_c, a))(proposals).astype(jnp.float32)
    return jnp.mean(jax.scipy.special.logsumexp(q_prop, axis=0) - qf_values)
