"""SAC losses (reference /root/reference/sheeprl/algos/sac/loss.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def critic_loss(qf_values: jax.Array, next_qf_value: jax.Array, num_critics: int) -> jax.Array:
    """Sum of per-critic MSE against the shared soft target
    (reference loss.py:9-18)."""
    del num_critics  # derived from the trailing axis
    return jnp.sum(jnp.mean((qf_values - next_qf_value) ** 2, axis=tuple(range(qf_values.ndim - 1))))


def policy_loss(alpha: jax.Array, logprobs: jax.Array, min_qf_values: jax.Array) -> jax.Array:
    """alpha*logpi - minQ (reference loss.py:21-24)."""
    return jnp.mean(alpha * logprobs - min_qf_values)


def entropy_loss(log_alpha: jax.Array, logprobs: jax.Array, target_entropy: float) -> jax.Array:
    """Automatic entropy-coefficient loss (reference loss.py:27-30)."""
    return jnp.mean(-log_alpha * (jax.lax.stop_gradient(logprobs) + target_entropy))
