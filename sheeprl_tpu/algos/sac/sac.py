"""SAC training loop — TPU-native re-design of
/root/reference/sheeprl/algos/sac/sac.py:32-427.

Off-policy machinery: host-side replay buffer (numpy/memmap), ``Ratio``-driven
gradient-step count per iteration, and a jitted update that runs ALL the
iteration's gradient steps as one ``lax.scan`` graph — each scan step does
critic update → Polyak target EMA → actor update → entropy(α) update
(reference sac.py:32-80), data-parallel over the mesh with ``pmean`` replacing
the DDP all-reduce (including the reference's explicit ``log_alpha`` grad
all-reduce, sac.py:72).
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.sac.agent import build_agent
from sheeprl_tpu.algos.sac.loss import conservative_q_penalty, critic_loss, entropy_loss, policy_loss
from sheeprl_tpu.algos.sac.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.envs.player import fetch_values, obs_sharding
from sheeprl_tpu.parallel.dp import local_sample_size
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, get_diagnostics, save_configs


def make_train_step(actor_def, critic_def, optimizers, cfg, mesh, target_entropy: float):
    """Jitted multi-gradient-step update over ``[G, B, ...]`` batches.

    The returned metric vector is ``[qf_loss, actor_loss, alpha_loss,
    grad_norm, nonfinite_steps]``; under
    ``diagnostics.sentinel.policy=skip_update`` a scan step whose losses or
    combined grad norm go non-finite has its whole critic/target/actor/alpha
    update discarded in-graph (the carry keeps its pre-step values).  With
    ``diagnostics.health`` on, a learn-health stats dict over the
    actor/critic/alpha module trio (grad/update/param norms, update/weight
    ratio, dead-unit fraction — averaged over the scan's gradient steps)
    rides the same output fetch; the combined grad norm is computed once
    there and shared with the sentinel's finiteness check.
    """
    from sheeprl_tpu.diagnostics.health import health_spec, health_stats
    from sheeprl_tpu.diagnostics.sentinel import finite_flag, select_finite, sentinel_spec

    sentinel = sentinel_spec(cfg)
    health = health_spec(cfg)
    world = mesh.devices.size
    distributed = world > 1
    tau = cfg.algo.tau
    cdt = compute_dtype_of(cfg)
    # conservative Q penalty (offline mode, howto/offline_rl.md): a
    # trace-time constant — cql_alpha=0 (the default, and every online run)
    # leaves the compiled graph bit-identical to the pre-offline step
    offline_cfg = cfg.algo.get("offline") or {}
    cql_alpha = float(offline_cfg.get("cql_alpha", 0.0) or 0.0)
    cql_samples = int(offline_cfg.get("cql_samples", 4) or 4)
    act_low = np.asarray(actor_def.action_low, np.float32).reshape(-1)
    act_high = np.asarray(actor_def.action_high, np.float32).reshape(-1)
    if cql_alpha > 0 and not (np.isfinite(act_low).all() and np.isfinite(act_high).all()):
        raise ValueError(
            "algo.offline.cql_alpha > 0 needs finite action bounds for its uniform "
            "action proposals (set algo.offline.action_low/high)"
        )

    def one_step(carry, inp):
        params, opt_states = carry
        batch, key = inp
        # snapshots for the sentinel's skip selection: tree_map rebuilds every
        # container (leaves shared), so the snapshot can never alias a dict
        # the update below mutates in place
        if sentinel.skip_update:
            prev_params = jax.tree_util.tree_map(lambda leaf: leaf, params)
            prev_opt_states = jax.tree_util.tree_map(lambda leaf: leaf, opt_states)
        # network inputs in the compute dtype; TD targets stay fp32
        obs_c = cast_floating(batch["observations"], cdt)
        next_obs_c = cast_floating(batch["next_observations"], cdt)
        # the cql key is split ONLY when the penalty is armed so the
        # cql_alpha=0 graph (and its RNG stream) stays bit-identical
        if cql_alpha > 0:
            key, cql_key = jax.random.split(key)

        # --- critic update (reference sac.py:45-53) -----------------------
        def qf_loss_fn(critic_params):
            next_actions, next_logprobs = actor_def.apply(
                cast_floating(params["actor"], cdt), next_obs_c, key, method="sample_and_log_prob"
            )
            next_q = critic_def.apply(
                cast_floating(params["target_critic"], cdt), next_obs_c, next_actions
            ).astype(jnp.float32)
            min_next_q = jnp.min(next_q, axis=-1, keepdims=True)
            alpha = jnp.exp(params["log_alpha"])
            next_qf_value = batch["rewards"] + (1 - batch["terminated"]) * cfg.algo.gamma * (
                min_next_q - alpha * next_logprobs.astype(jnp.float32)
            )
            next_qf_value = jax.lax.stop_gradient(next_qf_value)
            qf_values = critic_def.apply(
                cast_floating(critic_params, cdt), obs_c, cast_floating(batch["actions"], cdt)
            ).astype(jnp.float32)
            loss = critic_loss(qf_values, next_qf_value, cfg.algo.critic.n)
            if cql_alpha > 0:
                actor_c = cast_floating(params["actor"], cdt)
                critic_c = cast_floating(critic_params, cdt)
                loss = loss + cql_alpha * conservative_q_penalty(
                    cql_key,
                    obs_c,
                    qf_values,
                    lambda o, k: actor_def.apply(actor_c, o, k, method="sample_and_log_prob"),
                    lambda o, a: critic_def.apply(critic_c, o, a),
                    act_low,
                    act_high,
                    cql_samples,
                )
            return loss

        qf_l, qf_grads = jax.value_and_grad(qf_loss_fn)(params["critic"])
        if distributed:
            qf_grads = jax.lax.pmean(qf_grads, "data")
            qf_l = jax.lax.pmean(qf_l, "data")
        critic_updates, opt_states["critic"] = optimizers["critic"].update(
            qf_grads, opt_states["critic"], params["critic"]
        )
        params["critic"] = optax.apply_updates(params["critic"], critic_updates)

        # --- Polyak target EMA (reference sac.py:55-57, agent.py qfs_target_ema)
        params["target_critic"] = optax.incremental_update(
            params["critic"], params["target_critic"], tau
        )

        # --- actor update (reference sac.py:59-66) ------------------------
        def actor_loss_fn(actor_params):
            actions, logprobs = actor_def.apply(
                cast_floating(actor_params, cdt), obs_c, key, method="sample_and_log_prob"
            )
            q = critic_def.apply(cast_floating(params["critic"], cdt), obs_c, actions).astype(
                jnp.float32
            )
            min_q = jnp.min(q, axis=-1, keepdims=True)
            alpha = jnp.exp(params["log_alpha"])
            return policy_loss(alpha, logprobs.astype(jnp.float32), min_q), logprobs

        (actor_l, logprobs), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        if distributed:
            actor_grads = jax.lax.pmean(actor_grads, "data")
            actor_l = jax.lax.pmean(actor_l, "data")
        actor_updates, opt_states["actor"] = optimizers["actor"].update(
            actor_grads, opt_states["actor"], params["actor"]
        )
        params["actor"] = optax.apply_updates(params["actor"], actor_updates)

        # --- entropy coefficient update (reference sac.py:68-73) ----------
        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, logprobs, target_entropy)

        alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(params["log_alpha"])
        if distributed:
            alpha_grads = jax.lax.pmean(alpha_grads, "data")
            alpha_l = jax.lax.pmean(alpha_l, "data")
        alpha_updates, opt_states["alpha"] = optimizers["alpha"].update(
            alpha_grads, opt_states["alpha"], params["log_alpha"]
        )
        params["log_alpha"] = optax.apply_updates(params["log_alpha"], alpha_updates)

        # combined grad norm over the three sequential updates; a NaN/Inf in
        # any grad tree (or loss) poisons it, giving one scalar health flag.
        # health_stats over the {actor, critic, alpha} trio computes the
        # EXACT same combined norm, so the two layers share one reduction.
        if health.enabled:
            hstats = health_stats(
                {"actor": actor_grads, "critic": qf_grads, "alpha": alpha_grads},
                {"actor": actor_updates, "critic": critic_updates, "alpha": alpha_updates},
                {"actor": params["actor"], "critic": params["critic"], "alpha": params["log_alpha"]},
                per_module=health.per_module,
                dead_eps=health.dead_eps,
            )
            gnorm = hstats["grad_norm"]
        else:
            hstats = {}
            gnorm = jnp.sqrt(
                optax.global_norm(qf_grads) ** 2
                + optax.global_norm(actor_grads) ** 2
                + optax.global_norm(alpha_grads) ** 2
            )
        finite = finite_flag(gnorm, qf_l, actor_l, alpha_l)
        if sentinel.skip_update:
            params = select_finite(finite, params, prev_params)
            opt_states = select_finite(finite, opt_states, prev_opt_states)

        stats = jnp.stack([qf_l, actor_l, alpha_l, gnorm, 1.0 - finite.astype(jnp.float32)])
        return (params, opt_states), (stats, hstats)

    def update(params, opt_states, data, keys):
        (params, opt_states), (losses, health_tree) = jax.lax.scan(
            one_step, (params, opt_states), (data, keys)
        )
        # mean losses/grad-norm over gradient steps; nonfinite steps are a count
        metrics = jnp.concatenate([jnp.mean(losses[:, :4], axis=0), jnp.sum(losses[:, 4:], axis=0)])
        # health stats average over the scan's gradient steps and ride the
        # same output fetch as the metric vector
        return params, opt_states, metrics, jax.tree_util.tree_map(jnp.mean, health_tree)

    if distributed:
        from sheeprl_tpu.parallel.compat import shard_map

        def sharded(params, opt_states, data, keys):
            return shard_map(
                update,
                mesh=mesh,
                in_specs=(P(), P(), P(None, "data"), P()),
                out_specs=(P(), P(), P(), P()),
                check_vma=False,
            )(params, opt_states, data, keys)

        return jax.jit(sharded, donate_argnums=(0, 1))
    return jax.jit(update, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg):
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs

    if cfg.algo.cnn_keys.encoder:
        import warnings

        warnings.warn("SAC only uses vector observations; CNN keys are ignored (reference sac.py:100)")

    rng_key = runtime.seed_everything(cfg.seed)
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("SAC supports only continuous (Box) action spaces")
    mlp_keys = cfg.algo.mlp_keys.encoder

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    actor_def, critic_def, params, target_entropy = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)
    optimizers = {
        "actor": instantiate(cfg.algo.actor.optimizer),
        "critic": instantiate(cfg.algo.critic.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
    }
    opt_states = {
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
        "alpha": optimizers["alpha"].init(params["log_alpha"]),
    }
    if state and "opt_states" in state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            state["opt_states"],
        )

    from sheeprl_tpu.parallel.mesh import replicated_sharding

    if world_size > 1:
        params = jax.device_put(params, replicated_sharding(runtime.mesh))
        opt_states = jax.device_put(opt_states, replicated_sharding(runtime.mesh))

    train_step = diag.instrument(
        "train_step",
        make_train_step(actor_def, critic_def, optimizers, cfg, runtime.mesh, target_entropy),
        kind="train",
        donate_argnums=(0, 1),  # params, opt_states — audited at first dispatch
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)

    @jax.jit
    def policy_step(actor_params, obs, key):
        actions, _ = actor_def.apply(actor_params, obs, key, method="sample_and_log_prob")
        return actions

    policy_step = diag.instrument("policy_step", policy_step, kind="rollout")
    # one staged h2d + one blocking action fetch per vector step (see ppo.py)
    stage_sharding = obs_sharding(runtime.mesh if world_size > 1 else None)

    rb = ReplayBuffer(
        cfg.buffer.size,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=("observations",),
    )
    diag.track_buffer("replay", rb)
    if state and "rb" in state and state["rb"] is not None:
        rb.load_state_dict(state["rb"])

    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if cfg.checkpoint.resume_from:
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    batch_size = cfg.algo.per_rank_batch_size
    obs, _ = envs.reset(seed=cfg.seed)

    def run_train(iter_num: int, per_rank_gradient_steps: int) -> None:
        """Sample + dispatch this iteration's gradient steps and fetch the
        metrics (the blocking fetch included, so the whole thing can ride
        inside the env-step overlap window)."""
        nonlocal rng_key, params, opt_states
        with timer("Time/train_time"):
            with diag.span("buffer-sample"):
                sample = rb.sample(
                    batch_size=local_sample_size(batch_size * world_size),
                    n_samples=per_rank_gradient_steps,
                    sample_next_obs=cfg.buffer.sample_next_obs,
                )  # [G, B*world, ...]
                data = {
                    k: jnp.asarray(np.asarray(v), jnp.float32)
                    for k, v in sample.items()
                    if k in ("observations", "next_observations", "actions", "rewards", "terminated")
                }
            data = diag.maybe_inject_nan(iter_num, data)
            with diag.span("train"):
                rng_key, scan_key = jax.random.split(rng_key)
                keys = jax.random.split(scan_key, per_rank_gradient_steps)
                params, opt_states, losses, health = train_step(params, opt_states, data, keys)
                # one blocking d2h for metrics + health stats together
                losses, health_host = fetch_values(losses, health)
        diag.on_health(policy_step_count, health_host)
        aggregator.update("Loss/value_loss", float(losses[0]))
        aggregator.update("Loss/policy_loss", float(losses[1]))
        aggregator.update("Loss/alpha_loss", float(losses[2]))
        aggregator.update("Grads/global_norm", float(losses[3]))
        diag.on_update(
            policy_step_count,
            {
                "Loss/value_loss": float(losses[0]),
                "Loss/policy_loss": float(losses[1]),
                "Loss/alpha_loss": float(losses[2]),
                "Grads/global_norm": float(losses[3]),
            },
            nonfinite=float(losses[4]),
        )

    for iter_num in range(start_iter, total_iters + 1):
        policy_step_count += policy_steps_per_iter
        diag.note_env_steps(num_envs)
        with timer("Time/env_interaction_time"), diag.span("rollout"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                rng_key, step_key = jax.random.split(rng_key)
                flat_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs, sharding=stage_sharding)
                actions = np.asarray(policy_step(params["actor"], flat_obs, step_key))
            with diag.span("env_step_async"):
                envs.step_async(actions.reshape(envs.action_space.shape))

        # --- two-stage pipeline: gradient steps overlap the env workers ----
        # The sample sees transitions through t-1 (t's transition needs the
        # next obs, which is still being computed) — a bounded one-transition
        # lag (howto/async_envs.md) in exchange for a critical path of
        # max(train_dispatch + metric fetch, env_step) instead of their sum.
        # A still-empty buffer (learning_starts=0 first iteration) falls back
        # to training after the add, i.e. the serialized order.
        per_rank_gradient_steps = 0
        trained = False
        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step_count - prefill_steps * policy_steps_per_iter)
            if cfg.dry_run:
                per_rank_gradient_steps = 1
            if per_rank_gradient_steps > 0 and not rb.empty:
                run_train(iter_num, per_rank_gradient_steps)
                trained = True

        with timer("Time/env_interaction_time"), diag.span("env_wait"):
            next_obs, rewards, terminated, truncated, info = envs.step_wait()
        rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, -1)

        if "final_info" in info and "episode" in info["final_info"]:
            ep = info["final_info"]["episode"]
            mask = ep.get("_r", info["final_info"].get("_episode"))
            if mask is not None and np.any(mask):
                for r, l in zip(ep["r"][mask], ep["l"][mask]):
                    aggregator.update("Rewards/rew_avg", float(r))
                    aggregator.update("Game/ep_len_avg", float(l))

        # real next obs for done envs (reference sac.py:276-284)
        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
        if "final_obs" in info:
            for idx, final_obs in enumerate(info["final_obs"]):
                if final_obs is not None:
                    for k in mlp_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs[k])

        flat = {
            "observations": np.concatenate(
                [np.asarray(obs[k], np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
            ),
            "actions": actions.reshape(num_envs, -1),
            "rewards": rewards,
            "terminated": terminated,
            "truncated": truncated,
        }
        if not cfg.buffer.sample_next_obs:
            flat["next_observations"] = np.concatenate(
                [real_next_obs[k].astype(np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
            )
        step_data: Dict[str, np.ndarray] = step_slab(
            num_envs, flat, dtypes={"terminated": np.float32, "truncated": np.float32}
        )
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        obs = next_obs

        # --- train fallback (reference sac.py:299-355): only taken when the
        # pipelined site above skipped because the buffer was still empty ----
        if per_rank_gradient_steps > 0 and not trained:
            run_train(iter_num, per_rank_gradient_steps)

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "agent": jax.tree_util.tree_map(np.asarray, params),
                "opt_states": jax.tree_util.tree_map(np.asarray, opt_states),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": batch_size * world_size,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            with diag.span("checkpoint"):
                runtime.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            diag.on_checkpoint(policy_step_count, ckpt_path)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(actor_def.apply, params["actor"], test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    if cfg.model_manager.disabled is False and runtime.is_global_zero:  # pragma: no cover
        from sheeprl_tpu.utils.mlflow import log_models

        log_models(cfg, {"agent": params}, log_dir)
    logger.finalize()
    diag.close("completed")
