"""DreamerV1 losses (reference /root/reference/sheeprl/algos/dreamer_v1/loss.py)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v2.loss import normal_log_prob
from sheeprl_tpu.ops.distributions import Bernoulli


def kl_normal(p_mean, p_std, q_mean, q_std, event_dims: int = 1) -> jax.Array:
    """KL(N(p) || N(q)) summed over the stochastic axis (fp32)."""
    p_mean, p_std = p_mean.astype(jnp.float32), p_std.astype(jnp.float32)
    q_mean, q_std = q_mean.astype(jnp.float32), q_std.astype(jnp.float32)
    var_ratio = (p_std / q_std) ** 2
    t1 = ((p_mean - q_mean) / q_std) ** 2
    kl = 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return jnp.sum(kl, axis=tuple(range(-event_dims, 0)))


def reconstruction_loss(
    recon: Dict[str, jax.Array],
    observations: Dict[str, jax.Array],
    reward_mean: jax.Array,
    rewards: jax.Array,
    posterior_mean_std: Tuple[jax.Array, jax.Array],
    prior_mean_std: Tuple[jax.Array, jax.Array],
    kl_free_nats: float = 3.0,
    kl_regularizer: float = 1.0,
    qc: Optional[Bernoulli] = None,
    continue_targets: Optional[jax.Array] = None,
    continue_scale_factor: float = 10.0,
) -> Tuple[jax.Array, ...]:
    """Reference loss.py:40-100: Normal recon/reward, Gaussian KL with free
    nats applied to the mean."""
    observation_loss = -sum(
        jnp.mean(normal_log_prob(recon[k], observations[k], len(recon[k].shape[2:]))) for k in recon
    )
    reward_loss = -jnp.mean(normal_log_prob(reward_mean, rewards, 1))
    kl = jnp.mean(kl_normal(posterior_mean_std[0], posterior_mean_std[1], prior_mean_std[0], prior_mean_std[1]))
    state_loss = jnp.maximum(kl, kl_free_nats)
    if qc is not None and continue_targets is not None:
        continue_loss = continue_scale_factor * -jnp.mean(qc.log_prob(continue_targets))
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    rec_loss = kl_regularizer * state_loss + observation_loss + reward_loss + continue_loss
    return rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss
