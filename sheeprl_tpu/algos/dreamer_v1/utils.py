"""DreamerV1 helpers (reference /root/reference/sheeprl/algos/dreamer_v1/utils.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    last_values: jax.Array,
    horizon: int = 15,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV1 lambda targets over ``horizon-1`` steps (reference utils.py:42-77):
    the last step bootstraps the full ``last_values`` (no ``1-lambda``)."""
    next_vals = values[1 : horizon - 1] * (1 - lmbda)
    next_vals = jnp.concatenate([next_vals, last_values[None]], axis=0)  # [H-1]

    def body(agg, inp):
        r_t, nv_t, c_t = inp
        delta = r_t + nv_t * c_t
        agg = delta + lmbda * c_t * agg
        return agg, agg

    _, lv = jax.lax.scan(
        body,
        jnp.zeros_like(last_values),
        (rewards[: horizon - 1], next_vals, continues[: horizon - 1]),
        reverse=True,
    )
    return lv
