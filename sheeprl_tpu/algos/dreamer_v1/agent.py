"""DreamerV1 agent (reference /root/reference/sheeprl/algos/dreamer_v1/agent.py:64-547).

DV1's latent state is a **continuous diagonal Gaussian** (stochastic_size=30):
the representation/transition heads emit (mean, std) with
``std = softplus(raw) + min_std`` and the state is a reparameterized sample
(reference utils.py:80-108).  Encoder/decoder/actor/critic reuse the
parametric DV3 blocks with ELU/ReLU activations and no LayerNorm; the actor's
continuous distribution defaults to ``tanh_normal``.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.algos.dreamer_v3.agent import (
    Actor,
    CNNDecoderDV3,
    CNNEncoderDV3,
    Critic,
    DenseStack,
    MLPDecoderDV3,
    MLPEncoderDV3,
    PlayerDV3,
    RecurrentModel,
    trunc_normal_init,
    resolve_actor_cls,
)

PlayerDV1 = PlayerDV3


def gaussian_state(raw: jax.Array, key: Optional[jax.Array], min_std: float = 0.1, sample: bool = True):
    """(mean, std), rsample — reference dreamer_v1/utils.py:80-108."""
    mean, std = jnp.split(raw, 2, axis=-1)
    std = jax.nn.softplus(std) + min_std
    if sample:
        state = mean + std * jax.random.normal(key, mean.shape)
    else:
        state = mean
    return (mean, std), state


class GaussianRSSM(nn.Module):
    """Continuous-latent RSSM (reference agent.py:64-191).  No is_first
    resets: DV1's dynamic takes only (posterior, recurrent, action, embed)."""

    recurrent_state_size: int
    stochastic_size: int
    dense_units: int
    hidden_size: int
    min_std: float = 0.1
    act: str = "elu"

    def setup(self) -> None:
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            act=self.act,
            layer_norm=False,
            gru_layer_norm=False,
        )
        self.representation_model = _GaussHead(self.hidden_size, self.stochastic_size * 2, self.act)
        self.transition_model = _GaussHead(self.hidden_size, self.stochastic_size * 2, self.act)

    def __call__(self, posterior, recurrent_state, action, embedded_obs, key):
        return self.dynamic(posterior, recurrent_state, action, embedded_obs, key)

    def get_initial_states(self, batch_shape: Sequence[int]) -> Tuple[jax.Array, jax.Array]:
        h0 = jnp.zeros(tuple(batch_shape) + (self.recurrent_state_size,))
        z0 = jnp.zeros(tuple(batch_shape) + (self.stochastic_size,))
        return h0, z0

    def _representation(self, recurrent_state, embedded_obs, key):
        raw = self.representation_model(jnp.concatenate([recurrent_state, embedded_obs], axis=-1))
        return gaussian_state(raw, key, self.min_std)

    def _transition(self, recurrent_out, key, sample_state: bool = True):
        raw = self.transition_model(recurrent_out)
        return gaussian_state(raw, key, self.min_std, sample=sample_state)

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, key):
        """Reference agent.py:97-135: returns (recurrent, posterior, prior,
        posterior_mean_std, prior_mean_std)."""
        k1, k2 = jax.random.split(key)
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], axis=-1), recurrent_state
        )
        prior_mean_std, prior = self._transition(recurrent_state, k1)
        posterior_mean_std, posterior = self._representation(recurrent_state, embedded_obs, k2)
        return recurrent_state, posterior, prior, posterior_mean_std, prior_mean_std

    def imagination(self, stochastic_state, recurrent_state, actions, key):
        recurrent_state = self.recurrent_model(
            jnp.concatenate([stochastic_state, actions], axis=-1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key)
        return imagined_prior, recurrent_state


class _GaussHead(nn.Module):
    hidden_size: int
    out_size: int
    act: str = "elu"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.hidden_size, 1, act=self.act, layer_norm=False)(x)
        return nn.Dense(self.out_size, kernel_init=trunc_normal_init)(x)


class WorldModelDV1(nn.Module):
    """Encoder + GaussianRSSM + decoders + reward (+ continue) as one tree
    (reference agent.py:194-263 + build_agent :330-547)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_decoder_keys: Sequence[str]
    mlp_decoder_keys: Sequence[str]
    mlp_output_dims: Sequence[int]
    cnn_input_channels: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    cnn_stages: int
    encoder_dense_units: int
    encoder_mlp_layers: int
    decoder_dense_units: int
    decoder_mlp_layers: int
    recurrent_state_size: int
    stochastic_size: int
    rssm_dense_units: int
    rssm_hidden_size: int
    reward_dense_units: int
    reward_mlp_layers: int
    continue_dense_units: int
    continue_mlp_layers: int
    min_std: float = 0.1
    dense_act: str = "elu"
    cnn_act: str = "relu"

    # kept for PlayerDV3 compatibility
    discrete_size: int = 1
    decoupled_rssm: bool = False

    def setup(self) -> None:
        self.cnn_encoder = (
            CNNEncoderDV3(
                keys=tuple(self.cnn_keys),
                channels_multiplier=self.channels_multiplier,
                stages=self.cnn_stages,
                act=self.cnn_act,
                layer_norm=False,
            )
            if self.cnn_keys
            else None
        )
        self.mlp_encoder = (
            MLPEncoderDV3(
                keys=tuple(self.mlp_keys),
                dense_units=self.encoder_dense_units,
                mlp_layers=self.encoder_mlp_layers,
                symlog_inputs=False,
                act=self.dense_act,
                layer_norm=False,
            )
            if self.mlp_keys
            else None
        )
        self.rssm = GaussianRSSM(
            recurrent_state_size=self.recurrent_state_size,
            stochastic_size=self.stochastic_size,
            dense_units=self.rssm_dense_units,
            hidden_size=self.rssm_hidden_size,
            min_std=self.min_std,
            act=self.dense_act,
        )
        self.cnn_decoder = (
            CNNDecoderDV3(
                total_channels=int(sum(self.cnn_input_channels)),
                channels_multiplier=self.channels_multiplier,
                image_size=tuple(self.image_size),
                stages=self.cnn_stages,
                act=self.cnn_act,
                layer_norm=False,
            )
            if self.cnn_decoder_keys
            else None
        )
        self.mlp_decoder = (
            MLPDecoderDV3(
                keys=tuple(self.mlp_decoder_keys),
                output_dims=tuple(self.mlp_output_dims),
                dense_units=self.decoder_dense_units,
                mlp_layers=self.decoder_mlp_layers,
                act=self.dense_act,
                layer_norm=False,
            )
            if self.mlp_decoder_keys
            else None
        )
        self.reward_model = _GaussHeadStack(
            self.reward_dense_units, self.reward_mlp_layers, 1, self.dense_act
        )
        self.continue_model = _GaussHeadStack(
            self.continue_dense_units, self.continue_mlp_layers, 1, self.dense_act
        )

    def __call__(self, obs, action, is_first, key):
        del is_first  # DV1 has no is_first resets
        embedded = self.encode(obs)
        batch_shape = action.shape[:-1]
        posterior = jnp.zeros(batch_shape + (self.stochastic_size,))
        recurrent = jnp.zeros(batch_shape + (self.recurrent_state_size,))
        recurrent, posterior, prior, _, _ = self.rssm.dynamic(posterior, recurrent, action, embedded, key)
        latent = jnp.concatenate([posterior, recurrent], axis=-1)
        return self.decode(latent), self.reward_model(latent), self.continue_model(latent)

    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_encoder is not None:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            feats.append(self.mlp_encoder(obs))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            recon = self.cnn_decoder(latent)
            start = 0
            for k, c in zip(self.cnn_decoder_keys, self.cnn_input_channels):
                out[k] = recon[..., start : start + c, :, :]
                start += c
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent))
        return out

    def reward_logits(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent)

    def continue_logits(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent)

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, key):
        return self.rssm.dynamic(posterior, recurrent_state, action, embedded_obs, key)

    def imagination(self, prior, recurrent_state, actions, key):
        return self.rssm.imagination(prior, recurrent_state, actions, key)

    def initial_states(self, batch_shape: Sequence[int]):
        return self.rssm.get_initial_states(batch_shape)

    def representation(self, recurrent_state, embedded_obs, key):
        # PlayerDV3 expects (logits, state); return mean/std tuple in slot 0
        mean_std, state = self.rssm._representation(recurrent_state, embedded_obs, key)
        return mean_std, state

    def recurrent_step(self, stochastic, actions, recurrent_state):
        return self.rssm.recurrent_model(
            jnp.concatenate([stochastic, actions], axis=-1), recurrent_state
        )


class _GaussHeadStack(nn.Module):
    dense_units: int
    mlp_layers: int
    out_dim: int
    act: str = "elu"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.dense_units, self.mlp_layers, act=self.act, layer_norm=False)(x)
        return nn.Dense(self.out_dim, kernel_init=trunc_normal_init)(x)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
):
    """Returns (world_model_def, actor_def, critic_def, params)
    (reference agent.py:330-547; no target critic in DV1)."""
    wm_cfg = cfg.algo.world_model
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_decoder_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_decoder_keys = list(cfg.algo.mlp_keys.decoder)
    image_size = tuple(obs_space[cnn_keys[0]].shape[-2:]) if cnn_keys else (64, 64)
    cnn_stages = int(np.log2(cfg.env.screen_size) - np.log2(4)) if cnn_keys else 4
    latent_state_size = wm_cfg.stochastic_size + wm_cfg.recurrent_model.recurrent_state_size

    world_model_def = WorldModelDV1(
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_decoder_keys=tuple(cnn_decoder_keys),
        mlp_decoder_keys=tuple(mlp_decoder_keys),
        mlp_output_dims=tuple(int(prod(obs_space[k].shape)) for k in mlp_decoder_keys),
        cnn_input_channels=tuple(int(prod(obs_space[k].shape[:-2])) for k in cnn_decoder_keys),
        image_size=image_size,
        channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        cnn_stages=cnn_stages,
        encoder_dense_units=wm_cfg.encoder.dense_units,
        encoder_mlp_layers=wm_cfg.encoder.mlp_layers,
        decoder_dense_units=wm_cfg.observation_model.dense_units,
        decoder_mlp_layers=wm_cfg.observation_model.mlp_layers,
        recurrent_state_size=wm_cfg.recurrent_model.recurrent_state_size,
        stochastic_size=wm_cfg.stochastic_size,
        rssm_dense_units=wm_cfg.recurrent_model.dense_units,
        rssm_hidden_size=wm_cfg.representation_model.hidden_size,
        reward_dense_units=wm_cfg.reward_model.dense_units,
        reward_mlp_layers=wm_cfg.reward_model.mlp_layers,
        continue_dense_units=wm_cfg.discount_model.dense_units,
        continue_mlp_layers=wm_cfg.discount_model.mlp_layers,
        min_std=wm_cfg.min_std,
        dense_act="elu",
        cnn_act="relu",
    )
    # reference dv1 agent.py:472 / dv2 agent.py:1019: actor class from config
    actor_def = resolve_actor_cls(cfg.algo.actor)(
        latent_state_size=latent_state_size,
        actions_dim=tuple(int(a) for a in actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.type,
        init_std=cfg.algo.actor.init_std,
        min_std=cfg.algo.actor.min_std,
        dense_units=cfg.algo.actor.dense_units,
        mlp_layers=cfg.algo.actor.mlp_layers,
        unimix=0.0,
        action_clip=1.0,
        dense_act="elu",
        layer_norm=False,
        default_continuous_dist="tanh_normal",
    )
    critic_def = Critic(
        dense_units=cfg.algo.critic.dense_units,
        mlp_layers=cfg.algo.critic.mlp_layers,
        bins=1,
        act="elu",
        layer_norm=False,
        zero_init_head=False,
    )

    key = jax.random.PRNGKey(int(cfg.seed or 0))
    k_wm, k_actor, k_critic, k_call = jax.random.split(key, 4)
    sample_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        sample_obs[k] = jnp.zeros((1,) + tuple(obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, int(prod(obs_space[k].shape))), jnp.float32)
    sample_action = jnp.zeros((1, int(sum(actions_dim))), jnp.float32)
    wm_params = world_model_def.init(k_wm, sample_obs, sample_action, None, k_call)
    sample_latent = jnp.zeros((1, latent_state_size), jnp.float32)
    actor_params = actor_def.init(k_actor, sample_latent)
    critic_params = critic_def.init(k_critic, sample_latent)
    params = {"world_model": wm_params, "actor": actor_params, "critic": critic_params}
    if world_model_state is not None:
        params["world_model"] = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    if actor_state is not None:
        params["actor"] = jax.tree_util.tree_map(jnp.asarray, actor_state)
    if critic_state is not None:
        params["critic"] = jax.tree_util.tree_map(jnp.asarray, critic_state)
    return world_model_def, actor_def, critic_def, params
