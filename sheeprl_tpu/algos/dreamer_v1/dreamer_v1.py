"""DreamerV1 training loop — TPU-native re-design of
/root/reference/sheeprl/algos/dreamer_v1/dreamer_v1.py:46-750.

Same jitted-graph shape as DV3/DV2; DV1-specific math: Gaussian latents with
Normal-KL free nats, pure dynamics-backprop actor loss
(``-mean(discount * lambda_values)``), Normal(.,1) critic on ``horizon-1``
lambda targets, and no target critic.
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v1.agent import PlayerDV1, build_agent
from sheeprl_tpu.algos.dreamer_v1.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v1.utils import (  # noqa: F401
    AGGREGATOR_KEYS,
    MODELS_TO_REGISTER,
    compute_lambda_values,
    prepare_obs,
    test,
)
from sheeprl_tpu.algos.dreamer_v2.loss import normal_log_prob
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.data.factory import make_dreamer_replay_buffer
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.ops.distributions import Bernoulli
from sheeprl_tpu.parallel.dp import P, batch_spec, dp_axis, dp_jit, fold_key, pmean_tree, train_batches, local_sample_size
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import DeviceMetricsDrain, MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, get_diagnostics, save_configs

METRIC_ORDER = [
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "Loss/policy_loss",
    "Loss/value_loss",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
]


def make_train_step(world_model_def, actor_def, critic_def, optimizers, cfg, mesh=None):
    axis = dp_axis(mesh)
    cdt = compute_dtype_of(cfg)
    wm_cfg = cfg.algo.world_model
    stochastic_size = wm_cfg.stochastic_size
    recurrent_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    use_continues = wm_cfg.use_continues

    def train_step(params, opt_states, batch, key):
        T, B = batch["actions"].shape[:2]
        key = fold_key(key, axis)
        k_wm, k_img = jax.random.split(key)
        target_obs = {k: batch[k] for k in set(cnn_dec_keys + mlp_dec_keys)}  # fp32 targets
        batch_obs = cast_floating(target_obs, cdt)
        batch_actions = cast_floating(batch["actions"], cdt)

        def wm_loss_fn(wm_params):
            wm_params = cast_floating(wm_params, cdt)
            embedded = world_model_def.apply(wm_params, batch_obs, method="encode")

            def scan_body(carry, x):
                posterior, recurrent = carry
                action_t, embed_t, key_t = x
                recurrent, posterior, _, post_ms, prior_ms = world_model_def.apply(
                    wm_params, posterior, recurrent, action_t, embed_t, key_t, method="dynamic"
                )
                return (posterior, recurrent), (recurrent, posterior, post_ms, prior_ms)

            keys_t = jax.random.split(k_wm, T)
            init = (jnp.zeros((B, stochastic_size), cdt), jnp.zeros((B, recurrent_size), cdt))
            _, (recurrents, posteriors, post_ms, prior_ms) = jax.lax.scan(
                scan_body, init, (batch_actions, embedded, keys_t)
            )
            latents = jnp.concatenate([posteriors, recurrents], axis=-1)
            recon = world_model_def.apply(wm_params, latents, method="decode")
            reward_mean = world_model_def.apply(wm_params, latents, method="reward_logits")
            if use_continues:
                qc = Bernoulli(
                    world_model_def.apply(wm_params, latents, method="continue_logits"), event_dims=1
                )
                continues_targets = (1 - batch["terminated"]) * gamma
            else:
                qc = continues_targets = None
            rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                recon,
                target_obs,
                reward_mean,
                batch["rewards"],
                post_ms,
                prior_ms,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                qc,
                continues_targets,
                wm_cfg.continue_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrents": recurrents,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
            }
            return rec_loss, aux

        (rec_loss, aux), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(params["world_model"])
        wm_grads = pmean_tree(wm_grads, axis)
        updates, opt_states["world_model"] = optimizers["world_model"].update(
            wm_grads, opt_states["world_model"], params["world_model"]
        )
        params["world_model"] = optax.apply_updates(params["world_model"], updates)

        wm_params = cast_floating(params["world_model"], cdt)
        posteriors = jax.lax.stop_gradient(aux["posteriors"]).reshape(T * B, stochastic_size)
        recurrents = jax.lax.stop_gradient(aux["recurrents"]).reshape(T * B, recurrent_size)

        def actor_loss_fn(actor_params):
            actor_params = cast_floating(actor_params, cdt)
            latent0 = jnp.concatenate([posteriors, recurrents], axis=-1)

            def img_body(carry, key_t):
                prior, recurrent, latent = carry
                k_act, k_dyn = jax.random.split(key_t)
                actions = actor_def.apply(
                    actor_params, jax.lax.stop_gradient(latent), k_act, False, method="act"
                )
                prior, recurrent = world_model_def.apply(
                    wm_params, prior, recurrent, actions, k_dyn, method="imagination"
                )
                latent = jnp.concatenate([prior, recurrent], axis=-1)
                return (prior, recurrent, latent), latent

            keys_h = jax.random.split(k_img, horizon)
            _, latents_h = jax.lax.scan(img_body, (posteriors, recurrents, latent0), keys_h)
            imagined_trajectories = latents_h  # [H, TB, L] (reference keeps H states)

            predicted_values = critic_def.apply(
                cast_floating(params["critic"], cdt), imagined_trajectories
            ).astype(jnp.float32)
            predicted_rewards = world_model_def.apply(
                wm_params, imagined_trajectories, method="reward_logits"
            ).astype(jnp.float32)
            if use_continues:
                predicted_continues = jax.nn.sigmoid(
                    world_model_def.apply(wm_params, imagined_trajectories, method="continue_logits")
                ).astype(jnp.float32)
            else:
                predicted_continues = jnp.ones_like(jax.lax.stop_gradient(predicted_rewards)) * gamma

            lambda_values = compute_lambda_values(
                predicted_rewards,
                predicted_values,
                predicted_continues,
                last_values=predicted_values[-1],
                horizon=horizon,
                lmbda=cfg.algo.lmbda,
            )
            discount = jnp.cumprod(
                jnp.concatenate(
                    [jnp.ones_like(predicted_continues[:1]), predicted_continues[:-2]], axis=0
                ),
                axis=0,
            )
            discount = jax.lax.stop_gradient(discount)
            policy_loss = -jnp.mean(discount * lambda_values)
            aux2 = {
                "imagined_trajectories": jax.lax.stop_gradient(imagined_trajectories),
                "lambda_values": jax.lax.stop_gradient(lambda_values),
                "discount": discount,
            }
            return policy_loss, aux2

        (policy_loss, aux2), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        actor_grads = pmean_tree(actor_grads, axis)
        updates, opt_states["actor"] = optimizers["actor"].update(
            actor_grads, opt_states["actor"], params["actor"]
        )
        params["actor"] = optax.apply_updates(params["actor"], updates)

        imagined_trajectories = aux2["imagined_trajectories"]
        lambda_values = aux2["lambda_values"]
        discount = aux2["discount"]

        def critic_loss_fn(critic_params):
            values = critic_def.apply(cast_floating(critic_params, cdt), imagined_trajectories)[:-1]
            lp = normal_log_prob(values, lambda_values, 1)
            return -jnp.mean(discount[..., 0] * lp)

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_grads = pmean_tree(critic_grads, axis)
        updates, opt_states["critic"] = optimizers["critic"].update(
            critic_grads, opt_states["critic"], params["critic"]
        )
        params["critic"] = optax.apply_updates(params["critic"], updates)

        metrics = jnp.stack(
            [
                rec_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                policy_loss,
                value_loss,
                optax.global_norm(wm_grads),
                optax.global_norm(actor_grads),
                optax.global_norm(critic_grads),
            ]
        )
        metrics = pmean_tree(metrics, axis)
        return params, opt_states, metrics

    return dp_jit(
        train_step,
        mesh,
        in_specs=(P(), P(), batch_spec(batch_axis=1), P()),
        out_specs=(P(), P(), P()),
        donate_argnums=(0, 1),
    )


@register_algorithm()
def main(runtime, cfg):
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    cfg.env.frame_stack = 1

    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    rng_key = runtime.seed_everything(cfg.seed)

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    world_model_def, actor_def, critic_def, params = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"] if state else None,
        state["actor"] if state else None,
        state["critic"] if state else None,
    )
    player = PlayerDV1(world_model_def, actor_def, actions_dim, num_envs)

    optimizers = {
        "world_model": optax.chain(
            optax.clip_by_global_norm(cfg.algo.world_model.clip_gradients),
            instantiate(cfg.algo.world_model.optimizer),
        ),
        "actor": optax.chain(
            optax.clip_by_global_norm(cfg.algo.actor.clip_gradients),
            instantiate(cfg.algo.actor.optimizer),
        ),
        "critic": optax.chain(
            optax.clip_by_global_norm(cfg.algo.critic.clip_gradients),
            instantiate(cfg.algo.critic.optimizer),
        ),
    }
    opt_states = {
        "world_model": optimizers["world_model"].init(params["world_model"]),
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
    }
    if state and "opt_states" in state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            state["opt_states"],
        )

    from sheeprl_tpu.parallel.mesh import replicated_sharding

    if world_size > 1:
        params = jax.device_put(params, replicated_sharding(runtime.mesh))
        opt_states = jax.device_put(opt_states, replicated_sharding(runtime.mesh))

    # telemetry + memory instrumentation (watchdog, MFU FLOPs, transfer
    # guard, donation audit, OOM forensics) — see tools/check_instrumentation.py
    train_step = diag.instrument(
        "train_step",
        make_train_step(
            world_model_def, actor_def, critic_def, optimizers, cfg, mesh=runtime.mesh if world_size > 1 else None
        ),
        kind="train",
        donate_argnums=(0, 1),
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)

    buffer_size = cfg.buffer.size // num_envs if not cfg.dry_run else 4
    rb, use_device_buffer = make_dreamer_replay_buffer(
        cfg, world_size, num_envs, obs_keys, log_dir, buffer_size, mesh=runtime.mesh
    )
    if state and cfg.buffer.checkpoint and "rb" in state and state["rb"] is not None:
        rb.load_state_dict(state["rb"])

    train_step_count = 0
    last_train = 0
    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["iter_num"] * num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if cfg.checkpoint.resume_from:
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    obs = envs.reset(seed=cfg.seed)[0]
    step_data: Dict[str, np.ndarray] = step_slab(num_envs, {k: obs[k] for k in obs_keys})
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states(params["world_model"])

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)

    metrics_drain = DeviceMetricsDrain()

    for iter_num in range(start_iter, total_iters + 1):
        policy_step_count += policy_steps_per_iter

        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts and not cfg.checkpoint.resume_from:
                real_actions = actions = np.asarray(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
            else:
                rng_key, step_key = jax.random.split(rng_key)
                torch_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
                actions_jnp = player.get_actions(params["world_model"], params["actor"], torch_obs, step_key)
                actions = np.asarray(actions_jnp)
                if is_continuous:
                    real_actions = actions.reshape(num_envs, -1)
                else:
                    idxs = []
                    start = 0
                    for d in actions_dim:
                        idxs.append(np.argmax(actions[..., start : start + d], axis=-1))
                        start += d
                    real_actions = np.stack(idxs, axis=-1)

            step_data["actions"] = actions.reshape(1, num_envs, -1)
            rb.add(step_data, validate_args=cfg.buffer.validate_args)

            next_obs, rewards, terminated, truncated, infos = envs.step(
                real_actions.reshape(envs.action_space.shape)
            )
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "final_info" in infos and "episode" in infos["final_info"]:
            ep = infos["final_info"]["episode"]
            mask = ep.get("_r", infos["final_info"].get("_episode"))
            if mask is not None and np.any(mask):
                for r, l in zip(ep["r"][mask], ep["l"][mask]):
                    aggregator.update("Rewards/rew_avg", float(r))
                    aggregator.update("Game/ep_len_avg", float(l))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs[k])

        step_data.update(
            step_slab(
                num_envs,
                {
                    **{k: next_obs[k] for k in obs_keys},
                    "terminated": terminated,
                    "truncated": truncated,
                    "rewards": rewards,
                },
                dtypes={"terminated": np.float32, "truncated": np.float32, "rewards": np.float32},
            )
        )
        obs = next_obs
        if cfg.env.clip_rewards:
            step_data["rewards"] = np.tanh(step_data["rewards"])

        dones_idxes = dones.nonzero()[0].tolist()
        if dones_idxes:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = real_next_obs[k][dones_idxes][np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(sum(actions_dim))), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)
            step_data["rewards"][:, dones_idxes] = 0
            step_data["terminated"][:, dones_idxes] = 0
            step_data["truncated"][:, dones_idxes] = 0
            step_data["is_first"][:, dones_idxes] = 1
            reset_mask = np.zeros((num_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            player.init_states(params["world_model"], reset_mask)

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step_count - prefill_steps * policy_steps_per_iter)
            if cfg.dry_run:
                per_rank_gradient_steps = 1
            if per_rank_gradient_steps > 0:
                local_data = rb.sample(
                    local_sample_size(cfg.algo.per_rank_batch_size * world_size, use_device_buffer),
                    sequence_length=cfg.algo.per_rank_sequence_length,
                    n_samples=per_rank_gradient_steps,
                )

                batches = train_batches(
                    local_data,
                    per_rank_gradient_steps,
                    runtime.mesh if world_size > 1 else None,
                    cnn_keys,
                    use_device_buffer,
                )

                with timer("Time/train_time"):
                    for batch in batches:
                        rng_key, train_key = jax.random.split(rng_key)
                        params, opt_states, metrics = train_step(params, opt_states, batch, train_key)
                    train_step_count += 1
                metrics_drain.append(metrics)

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics_drain.flush_into(aggregator, METRIC_ORDER)
            metrics_dict = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/train_time", 0) > 0:
                metrics_dict["Time/sps_train"] = (train_step_count - last_train) / timers["Time/train_time"]
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics_dict["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) * cfg.env.action_repeat
                ) / timers["Time/env_interaction_time"]
            if runtime.is_global_zero:
                logger.log_metrics(metrics_dict, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count
            last_train = train_step_count

        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "world_model": jax.tree_util.tree_map(np.asarray, params["world_model"]),
                "actor": jax.tree_util.tree_map(np.asarray, params["actor"]),
                "critic": jax.tree_util.tree_map(np.asarray, params["critic"]),
                "opt_states": jax.tree_util.tree_map(np.asarray, opt_states),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        cumulative_rew = test(player, params["world_model"], params["actor"], runtime, cfg, log_dir, greedy=False)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    logger.finalize()
