"""Recurrent-PPO agent (reference /root/reference/sheeprl/algos/ppo_recurrent/agent.py:18-470).

Encoder → [pre-MLP] → LSTM → [post-MLP] → actor heads + critic.  The LSTM is
an `nn.OptimizedLSTMCell` stepped by `lax.scan` over the sequence axis — the
reference's cuDNN `nn.LSTM` + pack_padded_sequence machinery (agent.py:68-82)
is replaced by fixed-length sequences with in-graph state resets on done
(`reset_recurrent_state_on_done`), which keeps every shape static for XLA.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
from flax import linen as nn

from sheeprl_tpu.algos.ppo.agent import _CNNEncoder, _MLPEncoder
from sheeprl_tpu.models.blocks import MLP
from sheeprl_tpu.ops.distributions import Categorical, Normal


class _ResetLSTMCell(nn.Module):
    """LSTM cell that zeroes its carry where ``reset`` is 1 before stepping
    (the `reset_recurrent_state_on_done` semantics, in-graph)."""

    hidden_size: int

    @nn.compact
    def __call__(self, carry, inp):
        h, c = carry
        x_t, reset_t = inp
        h = h * (1 - reset_t)
        c = c * (1 - reset_t)
        (c, h), out = nn.OptimizedLSTMCell(features=self.hidden_size)((c, h), x_t)
        return (h, c), out


class RecurrentPPOAgent(nn.Module):
    """Sequence-level forward: obs leaves are ``[L, B, ...]``."""

    actions_dim: Sequence[int]
    is_continuous: bool
    cnn_keys: Sequence[str] = ()
    mlp_keys: Sequence[str] = ()
    encoder_cfg: Any = None
    rnn_cfg: Any = None
    actor_cfg: Any = None
    critic_cfg: Any = None

    def setup(self) -> None:
        enc = self.encoder_cfg
        self._cnn_enc = (
            _CNNEncoder(features_dim=enc["cnn_features_dim"], keys=tuple(self.cnn_keys)) if self.cnn_keys else None
        )
        self._mlp_enc = (
            _MLPEncoder(
                keys=tuple(self.mlp_keys),
                features_dim=enc["mlp_features_dim"],
                dense_units=enc["dense_units"],
                mlp_layers=enc.get("mlp_layers", 1) or 1,
                dense_act=enc.get("dense_act", "relu"),
                layer_norm=enc.get("layer_norm", True),
            )
            if self.mlp_keys
            else None
        )
        rnn = self.rnn_cfg
        self.lstm_hidden_size = rnn["lstm"]["hidden_size"]
        pre = rnn["pre_rnn_mlp"]
        self._pre_mlp = (
            MLP(
                hidden_sizes=[pre["dense_units"]],
                activation=pre.get("activation", "relu"),
                layer_norm=pre.get("layer_norm", False),
            )
            if pre["apply"]
            else None
        )
        post = rnn["post_rnn_mlp"]
        self._post_mlp = (
            MLP(
                hidden_sizes=[post["dense_units"]],
                activation=post.get("activation", "relu"),
                layer_norm=post.get("layer_norm", False),
            )
            if post["apply"]
            else None
        )
        self._cell = nn.scan(
            _ResetLSTMCell,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=0,
            out_axes=0,
        )(hidden_size=self.lstm_hidden_size)
        a = self.actor_cfg
        self.actor_backbone = MLP(
            hidden_sizes=[a["dense_units"]] * a["mlp_layers"],
            activation=a["dense_act"],
            layer_norm=a["layer_norm"],
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(int(sum(self.actions_dim)) * 2)]
        else:
            self.actor_heads = [nn.Dense(d) for d in self.actions_dim]
        c = self.critic_cfg
        self.critic = MLP(
            hidden_sizes=[c["dense_units"]] * c["mlp_layers"],
            output_dim=1,
            activation=c["dense_act"],
            layer_norm=c["layer_norm"],
        )

    def _features(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self._cnn_enc is not None:
            feats.append(self._cnn_enc(obs))
        if self._mlp_enc is not None:
            feats.append(self._mlp_enc(obs))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def rnn_scan(
        self,
        features: jax.Array,  # [L, B, F]
        prev_actions: jax.Array,  # [L, B, A]
        hx: jax.Array,  # [B, H]
        cx: jax.Array,  # [B, H]
        resets: Optional[jax.Array] = None,  # [L, B, 1] — 1 resets BEFORE step t
    ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
        x = jnp.concatenate([features, prev_actions], axis=-1)
        if self._pre_mlp is not None:
            x = self._pre_mlp(x)
        resets_seq = resets if resets is not None else jnp.zeros(x.shape[:2] + (1,))
        (hx, cx), outs = self._cell((hx, cx), (x, resets_seq))
        if self._post_mlp is not None:
            outs = self._post_mlp(outs)
        return outs, (hx, cx)

    def __call__(
        self,
        obs: Dict[str, jax.Array],
        prev_actions: jax.Array,
        hx: jax.Array,
        cx: jax.Array,
        resets: Optional[jax.Array] = None,
        key: Optional[jax.Array] = None,
        actions: Optional[jax.Array] = None,
        greedy: bool = False,
    ):
        """Return (actions, logprobs, entropies, values, (hx, cx)); everything
        ``[L, B, ...]``."""
        features = self._features(obs)
        out, (hx, cx) = self.rnn_scan(features, prev_actions, hx, cx, resets)
        values = self.critic(out)
        pre = self.actor_backbone(out)
        outs = [head(pre) for head in self.actor_heads]
        if self.is_continuous:
            mean, log_std = jnp.split(outs[0], 2, axis=-1)
            std = jnp.exp(log_std)
            dist = Normal(mean, std, event_dims=1)
            if actions is None:
                actions = dist.mode if greedy else dist.rsample(key)
            log_prob = dist.log_prob(actions)[..., None]
            entropy = dist.entropy()[..., None]
            return actions, log_prob, entropy, values, (hx, cx)
        sampled: List[jax.Array] = []
        log_probs: List[jax.Array] = []
        entropies: List[jax.Array] = []
        split_actions = (
            jnp.split(actions, len(self.actions_dim), axis=-1) if actions is not None else [None] * len(outs)
        )
        for i, logits in enumerate(outs):
            dist = Categorical(logits=logits)
            if split_actions[i] is None:
                if greedy:
                    act_idx = jnp.argmax(logits, axis=-1)
                else:
                    act_idx = dist.sample(jax.random.fold_in(key, i))
                act = act_idx[..., None].astype(jnp.float32)
            else:
                act = split_actions[i]
                act_idx = act[..., 0].astype(jnp.int32)
            sampled.append(act)
            log_probs.append(dist.log_prob(act_idx)[..., None])
            entropies.append(dist.entropy()[..., None])
        return (
            jnp.concatenate(sampled, axis=-1),
            jnp.sum(jnp.concatenate(log_probs, axis=-1), axis=-1, keepdims=True),
            jnp.sum(jnp.concatenate(entropies, axis=-1), axis=-1, keepdims=True),
            values,
            (hx, cx),
        )

    def get_values(self, obs, prev_actions, hx, cx, resets=None) -> jax.Array:
        features = self._features(obs)
        out, _ = self.rnn_scan(features, prev_actions, hx, cx, resets)
        return self.critic(out)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Dict[str, Any]] = None,
):
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    agent = RecurrentPPOAgent(
        actions_dim=tuple(int(a) for a in actions_dim),
        is_continuous=is_continuous,
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        encoder_cfg=cfg.algo.encoder,
        rnn_cfg=cfg.algo.rnn,
        actor_cfg=cfg.algo.actor,
        critic_cfg=cfg.algo.critic,
    )
    sample_obs = {}
    for k in cnn_keys:
        sample_obs[k] = jnp.zeros((1, 1) + tuple(obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, 1, prod(obs_space[k].shape)), jnp.float32)
    act_sum = int(sum(actions_dim))
    hx = jnp.zeros((1, cfg.algo.rnn.lstm.hidden_size), jnp.float32)
    params = agent.init(
        jax.random.PRNGKey(int(cfg.seed or 0)),
        sample_obs,
        jnp.zeros((1, 1, act_sum), jnp.float32),
        hx,
        hx,
        key=jax.random.PRNGKey(0),
    )
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    return agent, params, sample_obs
