"""Recurrent-PPO helper surface (reference /root/reference/sheeprl/algos/ppo_recurrent/utils.py)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.ppo.utils import prepare_obs as _ppo_prepare_obs

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
}
MODELS_TO_REGISTER = {"agent"}


def prepare_obs(
    obs: Dict[str, np.ndarray], *, cnn_keys: Sequence[str] = (), mlp_keys: Sequence[str] = (), num_envs: int = 1
) -> Dict[str, jax.Array]:
    """Like PPO's but with a leading sequence axis of 1: ``[1, N, ...]``."""
    out = _ppo_prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
    return {k: v[None] for k, v in out.items()}


def test(agent_apply, params, env, runtime, cfg, log_dir: str) -> float:
    """One greedy episode carrying LSTM state (reference utils.py:19-66)."""
    done = False
    cumulative_rew = 0.0
    obs, _ = env.reset(seed=cfg.seed)
    cnn_keys = cfg.algo.cnn_keys.encoder
    mlp_keys = cfg.algo.mlp_keys.encoder
    hidden = cfg.algo.rnn.lstm.hidden_size
    hx = jnp.zeros((1, hidden), jnp.float32)
    cx = jnp.zeros((1, hidden), jnp.float32)
    import gymnasium as gym

    if isinstance(env.action_space, gym.spaces.Discrete):
        actions_dim = [int(env.action_space.n)]
    elif isinstance(env.action_space, gym.spaces.MultiDiscrete):
        actions_dim = [int(d) for d in env.action_space.nvec]
    else:
        actions_dim = list(env.action_space.shape)
    act_sum = int(np.sum(actions_dim))
    prev_actions = jnp.zeros((1, 1, act_sum), jnp.float32)
    key = jax.random.PRNGKey(cfg.seed or 0)
    while not done:
        torch_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys)
        actions, _, _, _, (hx, cx) = agent_apply(
            params, torch_obs, prev_actions, hx, cx, key=key, greedy=True
        )
        actions_np = np.asarray(actions)
        if isinstance(env.action_space, gym.spaces.Box):
            prev_actions = actions
            env_actions = actions_np.reshape(env.action_space.shape)
        else:
            onehots = [
                np.eye(d, dtype=np.float32)[actions_np[0, :, j].astype(np.int64)]
                for j, d in enumerate(actions_dim)
            ]
            prev_actions = jnp.asarray(np.concatenate(onehots, axis=-1))[None]
            if isinstance(env.action_space, gym.spaces.Discrete):
                env_actions = int(actions_np[0, 0, 0])
            else:
                env_actions = actions_np[0, 0].astype(np.int64)
        obs, reward, terminated, truncated, _ = env.step(env_actions)
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    env.close()
    return cumulative_rew
