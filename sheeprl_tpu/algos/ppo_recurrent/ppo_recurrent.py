"""Recurrent-PPO training loop — TPU-native re-design of
/root/reference/sheeprl/algos/ppo_recurrent/ppo_recurrent.py:30-524.

The reference splits the rollout into episodes, pads them and trains with
`pack_padded_sequence` masking (ppo_recurrent.py:420-447).  Ragged episodes
are hostile to XLA's static shapes, so this build uses the equivalent
fixed-length formulation: the rollout ``[T, N]`` is cut into sequences of
``per_rank_sequence_length`` (T must be a multiple, like the reference
requires at :226), each sequence starts from its stored LSTM state, and the
`reset_recurrent_state_on_done` semantics are preserved by in-graph masked
state resets at done steps.  No padding, no masks, one `lax.scan` per BPTT.
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo_recurrent.agent import build_agent
from sheeprl_tpu.algos.ppo_recurrent.utils import (  # noqa: F401
    AGGREGATOR_KEYS,
    MODELS_TO_REGISTER,
    prepare_obs,
    test,
)
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.ops.numerics import gae
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import get_diagnostics, polynomial_decay, save_configs


def make_train_step(agent, optimizer, cfg, mesh, num_minibatches: int, seq_batch: int):
    """Jitted update over sequence minibatches: data leaves are
    ``[L, S, ...]`` with S sequences sharded over the mesh."""
    world = mesh.devices.size
    distributed = world > 1
    cdt = compute_dtype_of(cfg)
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    def loss_fn(params, batch, clip_coef, ent_coef, vf_coef):
        _, new_logprobs, entropy, new_values, _ = agent.apply(
            cast_floating(params, cdt),
            cast_floating({k: batch[k] for k in obs_keys}, cdt),
            cast_floating(batch["prev_actions"], cdt),
            cast_floating(batch["hx0"][0], cdt),
            cast_floating(batch["cx0"][0], cdt),
            resets=batch["resets"],
            actions=batch["actions"],
        )
        new_values = new_values.astype(jnp.float32)
        advantages = batch["advantages"]
        if cfg.algo.normalize_advantages:
            mu, std = advantages.mean(), advantages.std()
            if distributed:
                mu, std = jax.lax.pmean(mu, "data"), jax.lax.pmean(std, "data")
            advantages = (advantages - mu) / (std + 1e-8)
        pg_loss = policy_loss(new_logprobs, batch["logprobs"], advantages, clip_coef, "mean")
        v_loss = value_loss(
            new_values, batch["values"], batch["returns"], clip_coef, cfg.algo.clip_vloss, "mean"
        )
        e_loss = entropy_loss(entropy, cfg.algo.loss_reduction)
        return pg_loss + vf_coef * v_loss + ent_coef * e_loss, (pg_loss, v_loss, e_loss)

    def update(params, opt_state, data, key, coefs):
        clip_coef, ent_coef, vf_coef = coefs
        n_local = num_minibatches * seq_batch

        def epoch_body(carry, epoch_key):
            params, opt_state = carry
            perm = jax.random.permutation(epoch_key, n_local)
            idxs = perm.reshape(num_minibatches, seq_batch)

            def mb_body(carry, mb_idx):
                params, opt_state = carry
                mb = jax.tree_util.tree_map(lambda x: x[:, mb_idx], data)
                grads, aux = jax.grad(loss_fn, has_aux=True)(params, mb, clip_coef, ent_coef, vf_coef)
                if distributed:
                    grads = jax.lax.pmean(grads, "data")
                    aux = jax.lax.pmean(aux, "data")
                updates, opt_state = optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), jnp.stack(aux)

            return jax.lax.scan(mb_body, (params, opt_state), idxs)

        keys = jax.random.split(key, cfg.algo.update_epochs)
        (params, opt_state), losses = jax.lax.scan(epoch_body, (params, opt_state), keys)
        return params, opt_state, jnp.mean(losses.reshape(-1, 3), axis=0)

    if distributed:
        from sheeprl_tpu.parallel.compat import shard_map

        def sharded(params, opt_state, data, key, coefs):
            def body(params, opt_state, data, key, coefs):
                key = jax.random.fold_in(key, jax.lax.axis_index("data"))
                return update(params, opt_state, data, key, coefs)

            # every data leaf is [L|1, S, ...]: shard the sequence axis
            return shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), P(), P(None, "data"), P(), P()),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )(params, opt_state, data, key, coefs)

        return jax.jit(sharded, donate_argnums=(0, 1))
    return jax.jit(update, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg):
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    seq_len = cfg.algo.per_rank_sequence_length
    if not seq_len or seq_len <= 0:
        raise ValueError(f"per_rank_sequence_length must be positive, got {seq_len}")
    if rollout_steps % seq_len != 0:
        raise ValueError(
            f"rollout_steps ({rollout_steps}) must be a multiple of per_rank_sequence_length ({seq_len})"
        )
    num_sequences = (rollout_steps // seq_len) * num_envs
    if num_sequences % world_size != 0:
        raise ValueError(
            f"Number of sequences ({num_sequences}) must be divisible by the number of devices ({world_size})"
        )
    seq_per_device = num_sequences // world_size
    num_batches = max(1, cfg.algo.get("per_rank_num_batches", 4))
    seq_batch = max(1, seq_per_device // num_batches)
    num_minibatches = seq_per_device // seq_batch

    rng_key = runtime.seed_everything(cfg.seed)
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = cfg.algo.cnn_keys.encoder
    mlp_keys = cfg.algo.mlp_keys.encoder
    obs_keys = list(cnn_keys) + list(mlp_keys)
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    act_sum = int(sum(actions_dim)) if not is_continuous else int(np.prod(action_space.shape))

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    agent, params, _ = build_agent(
        runtime, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)
    policy_steps_per_iter = int(num_envs * rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    if cfg.algo.anneal_lr:
        schedule = optax.linear_schedule(
            init_value=cfg.algo.optimizer.learning_rate,
            end_value=0.0,
            transition_steps=max(1, total_iters * cfg.algo.update_epochs * num_minibatches),
        )
        base_opt = instantiate(cfg.algo.optimizer, learning_rate=schedule)
    else:
        base_opt = instantiate(cfg.algo.optimizer)
    chain = []
    if cfg.algo.max_grad_norm and cfg.algo.max_grad_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.algo.max_grad_norm))
    chain.append(base_opt)
    optimizer = optax.chain(*chain)
    opt_state = optimizer.init(params)
    if state and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_state,
            state["opt_state"],
        )

    # telemetry + memory instrumentation — see tools/check_instrumentation.py
    train_step = diag.instrument(
        "train_step",
        make_train_step(agent, optimizer, cfg, runtime.mesh, num_minibatches, seq_batch),
        kind="train",
        donate_argnums=(0, 1),
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_state)

    hidden = cfg.algo.rnn.lstm.hidden_size

    @jax.jit
    def policy_step(params, obs, prev_actions, hx, cx, key):
        actions, logprobs, _, values, (hx, cx) = agent.apply(
            params, obs, prev_actions, hx, cx, key=key
        )
        return actions, logprobs, values, hx, cx

    @jax.jit
    def value_step(params, obs, prev_actions, hx, cx):
        return agent.apply(params, obs, prev_actions, hx, cx, method="get_values")

    rb = ReplayBuffer(
        rollout_steps,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=obs_keys,
    )

    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0

    initial_ent = cfg.algo.ent_coef
    initial_clip = cfg.algo.clip_coef
    ent_coef = initial_ent
    clip_coef = initial_clip

    obs, _ = envs.reset(seed=cfg.seed)
    hx = jnp.zeros((num_envs, hidden), jnp.float32)
    cx = jnp.zeros((num_envs, hidden), jnp.float32)
    prev_actions_np = np.zeros((num_envs, act_sum), np.float32)
    prev_dones = np.zeros((num_envs, 1), np.float32)

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time"):
            for _ in range(rollout_steps):
                policy_step_count += num_envs
                rng_key, step_key = jax.random.split(rng_key)
                torch_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
                # reset state on done BEFORE stepping (reference resets at episode starts)
                if cfg.algo.reset_recurrent_state_on_done and prev_dones.any():
                    mask = jnp.asarray(1.0 - prev_dones, jnp.float32)
                    hx = hx * mask
                    cx = cx * mask
                    prev_actions_np = prev_actions_np * (1.0 - prev_dones)
                hx0_np = np.asarray(hx)
                cx0_np = np.asarray(cx)
                actions, logprobs, values, hx, cx = policy_step(
                    params, torch_obs, jnp.asarray(prev_actions_np)[None], hx, cx, step_key
                )
                actions_np = np.asarray(actions)[0]
                if is_continuous:
                    env_actions = actions_np.reshape(num_envs, -1)
                elif is_multidiscrete:
                    env_actions = actions_np.astype(np.int64)
                else:
                    env_actions = actions_np[:, 0].astype(np.int64)

                next_obs, rewards, terminated, truncated, info = envs.step(env_actions)
                dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)
                if cfg.env.clip_rewards:
                    rewards = np.tanh(rewards)

                step_data: Dict[str, np.ndarray] = step_slab(
                    num_envs,
                    {
                        **{k: obs[k] for k in obs_keys},
                        "actions": actions_np.reshape(num_envs, -1),
                        "prev_actions": prev_actions_np.reshape(num_envs, -1),
                        "logprobs": np.asarray(logprobs)[0].reshape(num_envs, -1),
                        "values": np.asarray(values)[0].reshape(num_envs, -1),
                        "rewards": rewards,
                        "dones": dones,
                        "resets": prev_dones,
                        "hx": hx0_np.reshape(num_envs, -1),
                        "cx": cx0_np.reshape(num_envs, -1),
                    },
                )
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                if "final_info" in info and "episode" in info["final_info"]:
                    ep = info["final_info"]["episode"]
                    mask = ep.get("_r", info["final_info"].get("_episode"))
                    if mask is not None and np.any(mask):
                        for r, l in zip(ep["r"][mask], ep["l"][mask]):
                            aggregator.update("Rewards/rew_avg", float(r))
                            aggregator.update("Game/ep_len_avg", float(l))

                # prev-action input to the RNN is one-hot for discrete heads
                # (reference ppo_recurrent.py:284,356: dim = sum(actions_dim))
                if is_continuous:
                    prev_actions_np = actions_np.reshape(num_envs, -1).astype(np.float32)
                else:
                    onehots = []
                    for j, d in enumerate(actions_dim):
                        onehots.append(np.eye(d, dtype=np.float32)[actions_np[:, j].astype(np.int64)])
                    prev_actions_np = np.concatenate(onehots, axis=-1)
                prev_dones = dones
                obs = next_obs

        # bootstrap + GAE (reference ppo_recurrent.py:358-396)
        local = {k: np.asarray(rb[k][:rollout_steps]) for k in rb.buffer.keys()}
        torch_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
        next_values = value_step(params, torch_obs, jnp.asarray(prev_actions_np)[None], hx, cx)
        returns, advantages = gae(
            jnp.asarray(local["rewards"]),
            jnp.asarray(local["values"]),
            jnp.asarray(local["dones"]),
            jnp.asarray(np.asarray(next_values)[0]),
            rollout_steps,
            cfg.algo.gamma,
            cfg.algo.gae_lambda,
        )
        local["returns"] = np.asarray(returns)
        local["advantages"] = np.asarray(advantages)

        # [T, N, ...] -> sequences [L, S, ...], S = (T/L)*N
        def to_seq(x):
            T, N = x.shape[:2]
            chunks = T // seq_len
            return (
                x.reshape(chunks, seq_len, N, *x.shape[2:])
                .swapaxes(1, 2)
                .reshape(chunks * N, seq_len, *x.shape[2:])
                .swapaxes(0, 1)
            )

        data = {k: to_seq(local[k]) for k in local.keys() if k not in ("hx", "cx")}
        # initial LSTM state of each sequence = stored state at its first step
        data["hx0"] = to_seq(local["hx"])[:1]
        data["cx0"] = to_seq(local["cx"])[:1]
        device_data = jax.tree_util.tree_map(jnp.asarray, data)
        if world_size > 1:
            from sheeprl_tpu.parallel.mesh import replicated_sharding
            from jax.sharding import NamedSharding

            seq_sharding = NamedSharding(runtime.mesh, P(None, "data"))
            device_data = jax.tree_util.tree_map(lambda x: jax.device_put(x, seq_sharding), device_data)

        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                iter_num, initial=initial_clip, final=0.0, max_decay_steps=total_iters, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                iter_num, initial=initial_ent, final=0.0, max_decay_steps=total_iters, power=1.0
            )

        with timer("Time/train_time"):
            rng_key, train_key = jax.random.split(rng_key)
            coefs = (
                jnp.asarray(clip_coef, jnp.float32),
                jnp.asarray(ent_coef, jnp.float32),
                jnp.asarray(cfg.algo.vf_coef, jnp.float32),
            )
            params, opt_state, losses = train_step(params, opt_state, device_data, train_key, coefs)
            losses = np.asarray(losses)

        aggregator.update("Loss/policy_loss", float(losses[0]))
        aggregator.update("Loss/value_loss", float(losses[1]))
        aggregator.update("Loss/entropy_loss", float(losses[2]))

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "agent": jax.tree_util.tree_map(np.asarray, params),
                "opt_state": jax.tree_util.tree_map(np.asarray, opt_state),
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": seq_batch * world_size,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            runtime.call("on_checkpoint_coupled", ckpt_path=ckpt_path, state=ckpt_state, replay_buffer=None)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(agent.apply, params, test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    logger.finalize()
