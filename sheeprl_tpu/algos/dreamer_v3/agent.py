"""DreamerV3 agent — TPU-native re-design of
/root/reference/sheeprl/algos/dreamer_v3/agent.py:42-1236.

Architecture parity with the reference (CNN/MLP encoders & decoders, the
RSSM with unimix + straight-through discrete latents, two-hot reward/critic
heads, Bernoulli continue head, scaled-normal/discrete actor, Hafner init),
re-expressed functionally:

- every model is a flax module over a params pytree; the "player" and
  "target critic" are not module copies with tied weights (reference
  agent.py:1190-1235) but simply *the same or EMA'd params values*;
- convolutions run NHWC (XLA-native TPU layout); the CHW buffer convention is
  transposed once inside the graph;
- the T-step dynamic unroll and H-step imagination are `jax.lax.scan` bodies
  built in the train step (../dreamer_v3/dreamer_v3.py), not Python loops;
- stochastic states are kept flattened [..., stochastic*discrete] and
  reshaped at the categorical boundaries.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.models.blocks import LayerNormGRUCell, get_activation
from sheeprl_tpu.ops.numerics import symlog

# Hafner initializers (reference algos/dreamer_v3/utils.py:143-188)
trunc_normal_init = nn.initializers.variance_scaling(1.0, "fan_avg", "truncated_normal")


def uniform_init(scale: float):
    if scale <= 0.0:
        return nn.initializers.zeros
    return nn.initializers.variance_scaling(scale, "fan_avg", "uniform")


class DenseStack(nn.Module):
    """[Dense(no bias iff LN) → LayerNorm(eps)? → act] × layers
    (the reference's MLP(…, bias=False, norm_layer=LayerNorm), agent.py:100-151).
    ``act``/``layer_norm`` are parametric so DreamerV2/V1 (ELU, no LN) reuse
    the same stack."""

    units: int
    layers: int
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        fn = get_activation(self.act)
        for _ in range(self.layers):
            x = nn.Dense(self.units, use_bias=not self.layer_norm, kernel_init=trunc_normal_init)(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.eps)(x)
            x = fn(x)
        return x


class CNNEncoderDV3(nn.Module):
    """4-stage stride-2 conv encoder (reference agent.py:42-100).  Input is the
    channel-concat of pixel keys in CHW; transposed to NHWC internally."""

    keys: Sequence[str]
    channels_multiplier: int
    stages: int = 4
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        fn = get_activation(self.act)
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3)
        lead = x.shape[:-3]
        x = x.reshape((-1,) + x.shape[-3:])
        x = jnp.transpose(x, (0, 2, 3, 1))  # CHW -> HWC
        for i in range(self.stages):
            x = nn.Conv(
                (2**i) * self.channels_multiplier,
                (4, 4),
                strides=(2, 2),
                padding=((1, 1), (1, 1)),
                use_bias=not self.layer_norm,
                kernel_init=trunc_normal_init,
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.eps)(x)  # channel-last LN: native in NHWC
            x = fn(x)
        return x.reshape(lead + (-1,))


class MLPEncoderDV3(nn.Module):
    """Symlog-input dense encoder (reference agent.py:100-151)."""

    keys: Sequence[str]
    dense_units: int
    mlp_layers: int
    eps: float = 1e-3
    symlog_inputs: bool = True
    act: str = "silu"
    layer_norm: bool = True

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([symlog(obs[k]) if self.symlog_inputs else obs[k] for k in self.keys], axis=-1)
        return DenseStack(self.dense_units, self.mlp_layers, self.eps, self.act, self.layer_norm)(x)


class CNNDecoderDV3(nn.Module):
    """Inverse of the encoder (reference agent.py:155-226): Linear projection
    to a 4x4 feature map, then stride-2 transposed convs back to image size.
    Returns the concatenated CHW reconstruction (split per key by caller)."""

    total_channels: int
    channels_multiplier: int
    image_size: Tuple[int, int]
    stages: int = 4
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True

    @nn.compact
    def __call__(self, latent: jax.Array) -> jax.Array:
        fn = get_activation(self.act)
        lead = latent.shape[:-1]
        start = self.image_size[0] // (2**self.stages)
        top_channels = (2 ** (self.stages - 1)) * self.channels_multiplier
        x = nn.Dense(start * start * (2 ** (self.stages - 1)) * self.channels_multiplier, kernel_init=trunc_normal_init)(
            latent
        )
        x = x.reshape((-1, start, start, top_channels))
        for i in range(self.stages - 1):
            x = nn.ConvTranspose(
                (2 ** (self.stages - i - 2)) * self.channels_multiplier,
                (4, 4),
                strides=(2, 2),
                padding="SAME",
                use_bias=not self.layer_norm,
                kernel_init=trunc_normal_init,
            )(x)
            if self.layer_norm:
                x = nn.LayerNorm(epsilon=self.eps)(x)
            x = fn(x)
        x = nn.ConvTranspose(
            self.total_channels, (4, 4), strides=(2, 2), padding="SAME", kernel_init=uniform_init(1.0)
        )(x)
        x = jnp.transpose(x, (0, 3, 1, 2))  # HWC -> CHW
        return x.reshape(lead + x.shape[1:])


class MLPDecoderDV3(nn.Module):
    """Dense decoder with one linear head per vector key (reference agent.py:229-280)."""

    keys: Sequence[str]
    output_dims: Sequence[int]
    dense_units: int
    mlp_layers: int
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True

    @nn.compact
    def __call__(self, latent: jax.Array) -> Dict[str, jax.Array]:
        x = DenseStack(self.dense_units, self.mlp_layers, self.eps, self.act, self.layer_norm)(latent)
        return {
            k: nn.Dense(d, kernel_init=uniform_init(1.0))(x) for k, d in zip(self.keys, self.output_dims)
        }


class RecurrentModel(nn.Module):
    """Dense projection + LayerNorm-GRU (reference agent.py:281-341)."""

    recurrent_state_size: int
    dense_units: int
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True
    gru_layer_norm: bool = True
    fused_gru: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, recurrent_state: jax.Array) -> jax.Array:
        feat = DenseStack(self.dense_units, 1, self.eps, self.act, self.layer_norm)(x)
        return LayerNormGRUCell(
            hidden_size=self.recurrent_state_size,
            use_bias=not self.gru_layer_norm,
            layer_norm=self.gru_layer_norm,
            norm_eps=self.eps,
            fused=self.fused_gru,
        )(recurrent_state, feat)


def _unimix(logits: jax.Array, discrete: int, unimix: float) -> jax.Array:
    """1% uniform-mix on the per-variable categorical logits
    (reference agent.py:437-449)."""
    shape = logits.shape
    logits = logits.reshape(shape[:-1] + (-1, discrete))
    if unimix > 0.0:
        probs = jax.nn.softmax(logits, axis=-1)
        uniform = jnp.ones_like(probs) / discrete
        probs = (1 - unimix) * probs + unimix * uniform
        logits = jnp.log(probs)
    return logits.reshape(shape)


def compute_stochastic_state(logits: jax.Array, discrete: int, key: Optional[jax.Array], sample: bool = True):
    """Straight-through sample of the [stoch, discrete] categorical block,
    returned flattened (reference algos/dreamer_v2/agent.py compute_stochastic_state)."""
    shape = logits.shape
    logits = logits.reshape(shape[:-1] + (-1, discrete))
    if sample:
        idx = jax.random.categorical(key, logits, axis=-1)
        hard = jax.nn.one_hot(idx, discrete, dtype=logits.dtype)
        probs = jax.nn.softmax(logits, axis=-1)
        out = hard + probs - jax.lax.stop_gradient(probs)  # straight-through
    else:
        idx = jnp.argmax(logits, axis=-1)
        out = jax.nn.one_hot(idx, discrete, dtype=logits.dtype)
    return out.reshape(shape)


class RSSM(nn.Module):
    """Recurrent State-Space Model (reference agent.py:344-498).

    Stochastic states flow flattened ``[..., stochastic*discrete]``.
    """

    recurrent_state_size: int
    stochastic_size: int
    discrete_size: int
    dense_units: int
    hidden_size: int
    embedded_obs_size: int
    unimix: float = 0.01
    eps: float = 1e-3
    learnable_initial_recurrent_state: bool = True
    decoupled: bool = False
    act: str = "silu"
    layer_norm: bool = True
    gru_layer_norm: bool = True
    head_scale: float = 1.0
    tanh_initial_state: bool = True
    fused_gru: bool = False

    def setup(self) -> None:
        self.recurrent_model = RecurrentModel(
            recurrent_state_size=self.recurrent_state_size,
            dense_units=self.dense_units,
            eps=self.eps,
            act=self.act,
            layer_norm=self.layer_norm,
            gru_layer_norm=self.gru_layer_norm,
            fused_gru=self.fused_gru,
        )
        stoch_flat = self.stochastic_size * self.discrete_size
        self.representation_model = _StochHead(
            self.hidden_size, stoch_flat, self.eps, self.act, self.layer_norm, self.head_scale
        )
        self.transition_model = _StochHead(
            self.hidden_size, stoch_flat, self.eps, self.act, self.layer_norm, self.head_scale
        )
        if self.learnable_initial_recurrent_state:
            self.initial_recurrent_state = self.param(
                "initial_recurrent_state", nn.initializers.zeros, (self.recurrent_state_size,)
            )
        else:
            self.initial_recurrent_state = jnp.zeros((self.recurrent_state_size,))

    def __call__(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        # init path: exercise every submodule
        return self.dynamic(posterior, recurrent_state, action, embedded_obs, is_first, key)

    def get_initial_states(self, batch_shape: Sequence[int]) -> Tuple[jax.Array, jax.Array]:
        h0 = jnp.tanh(self.initial_recurrent_state) if self.tanh_initial_state else self.initial_recurrent_state
        h0 = jnp.broadcast_to(h0, tuple(batch_shape) + h0.shape)
        logits = self.transition_model(h0)
        logits = _unimix(logits, self.discrete_size, self.unimix)
        z0 = compute_stochastic_state(logits, self.discrete_size, None, sample=False)
        return h0, z0

    def _representation(self, recurrent_state, embedded_obs, key):
        inp = (
            embedded_obs
            if self.decoupled
            else jnp.concatenate([recurrent_state, embedded_obs], axis=-1)
        )
        logits = _unimix(self.representation_model(inp), self.discrete_size, self.unimix)
        return logits, compute_stochastic_state(logits, self.discrete_size, key)

    def _transition(self, recurrent_out, key, sample_state: bool = True):
        logits = _unimix(self.transition_model(recurrent_out), self.discrete_size, self.unimix)
        return logits, compute_stochastic_state(logits, self.discrete_size, key, sample=sample_state)

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        """One step of dynamic learning (reference agent.py:396-435).
        All states flattened; ``is_first`` resets to the learned initial state."""
        k1, k2 = jax.random.split(key)
        action = (1 - is_first) * action
        initial_recurrent, initial_posterior = self.get_initial_states(recurrent_state.shape[:-1])
        recurrent_state = (1 - is_first) * recurrent_state + is_first * initial_recurrent
        posterior = (1 - is_first) * posterior + is_first * initial_posterior
        recurrent_state = self.recurrent_model(
            jnp.concatenate([posterior, action], axis=-1), recurrent_state
        )
        prior_logits, prior = self._transition(recurrent_state, k1)
        posterior_logits, posterior = self._representation(recurrent_state, embedded_obs, k2)
        return recurrent_state, posterior, prior, posterior_logits, prior_logits

    def imagination(self, prior, recurrent_state, actions, key):
        """One-step latent imagination (reference agent.py:478-498)."""
        recurrent_state = self.recurrent_model(
            jnp.concatenate([prior, actions], axis=-1), recurrent_state
        )
        _, imagined_prior = self._transition(recurrent_state, key)
        return imagined_prior, recurrent_state


class _StochHead(nn.Module):
    """hidden dense stack + linear head to the stochastic logits, Hafner
    uniform(1.0) head init (reference build_agent, agent.py:1178-1183)."""

    hidden_size: int
    out_size: int
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True
    head_scale: float = 1.0

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.hidden_size, 1, self.eps, self.act, self.layer_norm)(x)
        init = uniform_init(self.head_scale) if self.head_scale != -1 else trunc_normal_init
        return nn.Dense(self.out_size, kernel_init=init)(x)


class PredictionHead(nn.Module):
    """MLP + linear head used by reward (zero-init), continue (uniform 1.0)
    and critic (zero-init) models (reference build_agent, agent.py:1100-1140)."""

    dense_units: int
    mlp_layers: int
    out_dim: int
    head_scale: float = 0.0
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.dense_units, self.mlp_layers, self.eps, self.act, self.layer_norm)(x)
        init = uniform_init(self.head_scale) if self.head_scale != -1 else trunc_normal_init
        return nn.Dense(self.out_dim, kernel_init=init)(x)


class WorldModel(nn.Module):
    """Encoder + RSSM + decoders + reward + continue as ONE module/params tree
    (the reference's `WorldModel` container, dreamer_v2/agent.py:707-732, keeps
    them separate modules under one optimizer; one tree == one optimizer)."""

    cnn_keys: Sequence[str]
    mlp_keys: Sequence[str]
    cnn_decoder_keys: Sequence[str]
    mlp_decoder_keys: Sequence[str]
    mlp_output_dims: Sequence[int]
    cnn_input_channels: Sequence[int]
    image_size: Tuple[int, int]
    channels_multiplier: int
    cnn_stages: int
    encoder_dense_units: int
    encoder_mlp_layers: int
    decoder_dense_units: int
    decoder_mlp_layers: int
    recurrent_state_size: int
    stochastic_size: int
    discrete_size: int
    rssm_dense_units: int
    rssm_hidden_size: int
    reward_dense_units: int
    reward_mlp_layers: int
    reward_bins: int
    continue_dense_units: int
    continue_mlp_layers: int
    unimix: float = 0.01
    eps: float = 1e-3
    learnable_initial_recurrent_state: bool = True
    decoupled_rssm: bool = False
    dense_act: str = "silu"
    cnn_act: str = "silu"
    layer_norm: bool = True
    gru_layer_norm: bool = True
    symlog_inputs: bool = True
    hafner_heads: bool = True  # uniform/zero head inits (DV3); -1 sentinel = default init
    fused_gru: bool = False  # Pallas fused LayerNorm-GRU cell (TPU)

    def setup(self) -> None:
        self.cnn_encoder = (
            CNNEncoderDV3(
                keys=tuple(self.cnn_keys),
                channels_multiplier=self.channels_multiplier,
                stages=self.cnn_stages,
                eps=self.eps,
                act=self.cnn_act,
                layer_norm=self.layer_norm,
            )
            if self.cnn_keys
            else None
        )
        self.mlp_encoder = (
            MLPEncoderDV3(
                keys=tuple(self.mlp_keys),
                dense_units=self.encoder_dense_units,
                mlp_layers=self.encoder_mlp_layers,
                eps=self.eps,
                symlog_inputs=self.symlog_inputs,
                act=self.dense_act,
                layer_norm=self.layer_norm,
            )
            if self.mlp_keys
            else None
        )
        embedded = 0
        if self.cnn_keys:
            embedded += (2 ** (self.cnn_stages - 1)) * self.channels_multiplier * (
                self.image_size[0] // (2**self.cnn_stages)
            ) * (self.image_size[1] // (2**self.cnn_stages))
        if self.mlp_keys:
            embedded += self.encoder_dense_units
        self.rssm = RSSM(
            recurrent_state_size=self.recurrent_state_size,
            stochastic_size=self.stochastic_size,
            discrete_size=self.discrete_size,
            dense_units=self.rssm_dense_units,
            hidden_size=self.rssm_hidden_size,
            embedded_obs_size=embedded,
            unimix=self.unimix,
            eps=self.eps,
            learnable_initial_recurrent_state=self.learnable_initial_recurrent_state,
            decoupled=self.decoupled_rssm,
            act=self.dense_act,
            layer_norm=self.layer_norm,
            gru_layer_norm=self.gru_layer_norm,
            head_scale=1.0 if self.hafner_heads else -1,
            tanh_initial_state=self.learnable_initial_recurrent_state,
            fused_gru=self.fused_gru,
        )
        self.cnn_decoder = (
            CNNDecoderDV3(
                total_channels=int(sum(self.cnn_input_channels)),
                channels_multiplier=self.channels_multiplier,
                image_size=tuple(self.image_size),
                stages=self.cnn_stages,
                eps=self.eps,
                act=self.cnn_act,
                layer_norm=self.layer_norm,
            )
            if self.cnn_decoder_keys
            else None
        )
        self.mlp_decoder = (
            MLPDecoderDV3(
                keys=tuple(self.mlp_decoder_keys),
                output_dims=tuple(self.mlp_output_dims),
                dense_units=self.decoder_dense_units,
                mlp_layers=self.decoder_mlp_layers,
                eps=self.eps,
                act=self.dense_act,
                layer_norm=self.layer_norm,
            )
            if self.mlp_decoder_keys
            else None
        )
        self.reward_model = PredictionHead(
            self.reward_dense_units,
            self.reward_mlp_layers,
            self.reward_bins,
            head_scale=0.0 if self.hafner_heads else -1,
            eps=self.eps,
            act=self.dense_act,
            layer_norm=self.layer_norm,
        )
        self.continue_model = PredictionHead(
            self.continue_dense_units,
            self.continue_mlp_layers,
            1,
            head_scale=1.0 if self.hafner_heads else -1,
            eps=self.eps,
            act=self.dense_act,
            layer_norm=self.layer_norm,
        )

    # -- init path ----------------------------------------------------------
    def __call__(self, obs, action, is_first, key):
        embedded = self.encode(obs)
        batch_shape = action.shape[:-1]
        stoch_flat = self.stochastic_size * self.discrete_size
        posterior = jnp.zeros(batch_shape + (stoch_flat,))
        recurrent = jnp.zeros(batch_shape + (self.recurrent_state_size,))
        recurrent, posterior, prior, post_logits, prior_logits = self.rssm.dynamic(
            posterior, recurrent, action, embedded, is_first, key
        )
        latent = jnp.concatenate([posterior, recurrent], axis=-1)
        recon = self.decode(latent)
        reward = self.reward_model(latent)
        cont = self.continue_model(latent)
        return recon, reward, cont

    # -- public methods (used via apply(..., method=...)) -------------------
    def encode(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self.cnn_encoder is not None:
            feats.append(self.cnn_encoder(obs))
        if self.mlp_encoder is not None:
            feats.append(self.mlp_encoder(obs))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def decode(self, latent: jax.Array) -> Dict[str, jax.Array]:
        out: Dict[str, jax.Array] = {}
        if self.cnn_decoder is not None:
            recon = self.cnn_decoder(latent)
            start = 0
            for k, c in zip(self.cnn_decoder_keys, self.cnn_input_channels):
                out[k] = recon[..., start : start + c, :, :]
                start += c
        if self.mlp_decoder is not None:
            out.update(self.mlp_decoder(latent))
        return out

    def reward_logits(self, latent: jax.Array) -> jax.Array:
        return self.reward_model(latent)

    def continue_logits(self, latent: jax.Array) -> jax.Array:
        return self.continue_model(latent)

    def dynamic(self, posterior, recurrent_state, action, embedded_obs, is_first, key):
        return self.rssm.dynamic(posterior, recurrent_state, action, embedded_obs, is_first, key)

    def imagination(self, prior, recurrent_state, actions, key):
        return self.rssm.imagination(prior, recurrent_state, actions, key)

    def initial_states(self, batch_shape: Sequence[int]):
        return self.rssm.get_initial_states(batch_shape)

    def representation(self, recurrent_state, embedded_obs, key):
        return self.rssm._representation(recurrent_state, embedded_obs, key)

    def recurrent_step(self, stochastic, actions, recurrent_state):
        return self.rssm.recurrent_model(
            jnp.concatenate([stochastic, actions], axis=-1), recurrent_state
        )


class Actor(nn.Module):
    """DV3 actor (reference agent.py:694-845): MLP backbone + one head per
    discrete sub-action (unimix + straight-through) or a single
    (mean, std) head for continuous (`scaled_normal`/`tanh_normal`)."""

    latent_state_size: int
    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    init_std: float = 2.0
    min_std: float = 0.1
    max_std: float = 1.0
    dense_units: int = 1024
    mlp_layers: int = 5
    unimix: float = 0.01
    action_clip: float = 1.0
    eps: float = 1e-3
    dense_act: str = "silu"
    layer_norm: bool = True
    default_continuous_dist: str = "scaled_normal"  # DV2/DV1 use trunc_normal/tanh_normal

    def setup(self) -> None:
        dist = self.distribution.lower()
        if dist not in ("auto", "normal", "tanh_normal", "discrete", "scaled_normal", "trunc_normal"):
            raise ValueError(f"Invalid actor distribution: {dist}")
        if dist == "auto":
            dist = self.default_continuous_dist if self.is_continuous else "discrete"
        self.dist = dist
        self.model = DenseStack(self.dense_units, self.mlp_layers, self.eps, self.dense_act, self.layer_norm)
        if self.is_continuous:
            self.heads = [nn.Dense(int(sum(self.actions_dim)) * 2, kernel_init=uniform_init(1.0))]
        else:
            self.heads = [nn.Dense(d, kernel_init=uniform_init(1.0)) for d in self.actions_dim]

    def __call__(self, state: jax.Array) -> Sequence[jax.Array]:
        """Return the raw head outputs (`pre_dist`)."""
        x = self.model(state)
        return [h(x) for h in self.heads]

    def _continuous_dist_params(self, pre: jax.Array):
        mean, std = jnp.split(pre, 2, axis=-1)
        if self.dist == "tanh_normal":
            mean = 5 * jnp.tanh(mean / 5)
            std = jax.nn.softplus(std + self.init_std) + self.min_std
        elif self.dist == "scaled_normal":
            std = (self.max_std - self.min_std) * jax.nn.sigmoid(std + self.init_std) + self.min_std
            mean = jnp.tanh(mean)
        elif self.dist == "trunc_normal":
            # DreamerV2 continuous actor (reference dreamer_v2/agent.py:536-539)
            std = 2 * jax.nn.sigmoid((std + self.init_std) / 2) + self.min_std
            mean = jnp.tanh(mean)
        return mean, std

    def act(
        self,
        state: jax.Array,
        key: Optional[jax.Array] = None,
        greedy: bool = False,
        mask=None,
    ) -> jax.Array:
        """Sample (or take the mode of) the actions, concatenated over heads.
        ``mask`` is accepted for interface parity (reference agent.py:786) and
        ignored; ``MinedojoActor`` consumes it."""
        pre_dist = self(state)
        if self.is_continuous:
            mean, std = self._continuous_dist_params(pre_dist[0])
            if greedy:
                # the reference draws 100 samples and keeps the most likely
                # (agent.py:817-821); the mode of the (tanh-)normal is cheaper
                # and deterministic
                actions = mean
            else:
                if self.dist == "trunc_normal":
                    from sheeprl_tpu.ops.distributions import TruncatedNormal

                    actions = TruncatedNormal(mean, std, -1.0, 1.0).rsample(key)
                else:
                    actions = mean + std * jax.random.normal(key, mean.shape)
            if self.dist == "tanh_normal":
                actions = jnp.tanh(actions)
            if self.action_clip > 0.0:
                clip = jnp.full_like(actions, self.action_clip)
                actions = actions * jax.lax.stop_gradient(clip / jnp.maximum(clip, jnp.abs(actions)))
            return actions
        outs = []
        functional_action = None
        for i, logits in enumerate(pre_dist):
            logits = _unimix(logits, logits.shape[-1], self.unimix)
            # mask hook: identity here; MinedojoActor injects its hierarchy
            # (unused functional_action/argmax chains are DCE'd by XLA)
            logits = self._masked_logits_for_head(i, logits, functional_action, mask)
            if greedy:
                idx = jnp.argmax(logits, axis=-1)
                one_hot = jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)
            else:
                sub_key = jax.random.fold_in(key, i)
                idx = jax.random.categorical(sub_key, logits, axis=-1)
                hard = jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)
                probs = jax.nn.softmax(logits, axis=-1)
                one_hot = hard + probs - jax.lax.stop_gradient(probs)
            outs.append(one_hot)
            if functional_action is None:
                functional_action = jnp.argmax(outs[0], axis=-1)
        return jnp.concatenate(outs, axis=-1)

    def _masked_logits_for_head(
        self, i: int, logits: jax.Array, functional_action: Optional[jax.Array], mask
    ) -> jax.Array:
        """Per-head logit hook for hierarchical masking; base actor: identity."""
        del i, functional_action, mask
        return logits

    def log_prob_entropy(self, state: jax.Array, actions: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Log-prob of given (concatenated) actions + policy entropy, both
        ``[..., 1]`` (reference train, dreamer_v3.py:280-297)."""
        pre_dist = self(state)
        if self.is_continuous:
            mean, std = self._continuous_dist_params(pre_dist[0])
            if self.dist == "tanh_normal":
                from sheeprl_tpu.ops.numerics import safeatanh

                x = safeatanh(actions, 1e-6)
                var = std**2
                lp = -((x - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)
                lp = lp - jnp.log1p(-(actions**2) + 1e-6)
                log_prob = jnp.sum(lp, axis=-1, keepdims=True)
                ent = -log_prob  # no closed form for tanh-normal entropy
                return log_prob, ent
            if self.dist == "trunc_normal":
                from sheeprl_tpu.ops.distributions import TruncatedNormal

                d = TruncatedNormal(mean, std, -1.0, 1.0, event_dims=1)
                return d.log_prob(actions)[..., None], d.entropy()[..., None]
            var = std**2
            lp = -((actions - mean) ** 2) / (2 * var) - jnp.log(std) - 0.5 * jnp.log(2 * jnp.pi)
            log_prob = jnp.sum(lp, axis=-1, keepdims=True)
            ent = jnp.sum(0.5 + 0.5 * jnp.log(2 * jnp.pi) + jnp.log(std), axis=-1, keepdims=True)
            return log_prob, ent
        log_probs = []
        entropies = []
        start = 0
        for i, logits in enumerate(pre_dist):
            d = logits.shape[-1]
            logits = _unimix(logits, d, self.unimix)
            logits = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)
            act = actions[..., start : start + d]
            start += d
            log_probs.append(jnp.sum(act * logits, axis=-1, keepdims=True))
            p = jnp.exp(logits)
            entropies.append(-jnp.sum(p * logits, axis=-1, keepdims=True))
        return (
            sum(log_probs),
            sum(entropies),
        )


class MinedojoActor(Actor):
    """Hierarchically masked actor for MineDojo (reference agent.py:848-932).

    MineDojo's MultiDiscrete action space is [action_type(19), craft_arg,
    equip/place/destroy_arg]; the env publishes per-step validity masks as
    ``mask_*`` observation keys (envs/minedojo.py).  Head 0 (action type) is
    masked with ``mask_action_type``; head 1 (craft arg) is masked with
    ``mask_craft_smelt`` only where the *sampled* action type is 15 (craft);
    head 2 (destroy/equip/place arg) is masked with ``mask_equip_place``
    where the sampled type is 16/17 and with ``mask_destroy`` where it is 18
    (reference mask application at agent.py:905-928).  Masked categories get
    ``-inf`` logits AFTER the unimix transform, so the remaining categories'
    unimix-smoothed probabilities renormalize through the softmax.

    The reference's per-(t, b) Python loops become vectorized ``jnp.where``
    selections — the conditional masks depend only on the sampled functional
    action, which is data, not control flow, so the whole hierarchy stays
    inside one jitted graph.  The sampling loop itself is the base
    ``Actor.act``; only the per-head logit hook is overridden, so the
    straight-through/unimix semantics can never diverge between the two.
    """

    # MineDojo composite action-type indices that gate the argument heads
    CRAFT_ACTION = 15
    EQUIP_ACTION = 16
    PLACE_ACTION = 17
    DESTROY_ACTION = 18

    def _masked_logits_for_head(
        self, i: int, logits: jax.Array, functional_action: Optional[jax.Array], mask
    ) -> jax.Array:
        neg_inf = jnp.array(-jnp.inf, logits.dtype)
        if mask is None:
            return logits
        if i == 0:
            allowed = jnp.broadcast_to(mask["mask_action_type"].astype(bool), logits.shape)
        elif i == 1:
            craft = functional_action == self.CRAFT_ACTION  # [...]
            allowed = jnp.where(
                craft[..., None],
                jnp.broadcast_to(mask["mask_craft_smelt"].astype(bool), logits.shape),
                True,
            )
        elif i == 2:
            equip_place = (functional_action == self.EQUIP_ACTION) | (
                functional_action == self.PLACE_ACTION
            )
            destroy = functional_action == self.DESTROY_ACTION
            allowed = jnp.where(
                equip_place[..., None],
                jnp.broadcast_to(mask["mask_equip_place"].astype(bool), logits.shape),
                jnp.where(
                    destroy[..., None],
                    jnp.broadcast_to(mask["mask_destroy"].astype(bool), logits.shape),
                    True,
                ),
            )
        else:
            return logits
        return jnp.where(allowed, logits, neg_inf)

    def setup(self) -> None:
        if self.is_continuous:
            raise ValueError("MinedojoActor only supports discrete (MultiDiscrete) action spaces")
        super().setup()


class Critic(nn.Module):
    """Two-hot critic (reference build_agent, agent.py:1155-1175): MLP +
    zero-initialized bins head."""

    dense_units: int
    mlp_layers: int
    bins: int = 255
    eps: float = 1e-3
    act: str = "silu"
    layer_norm: bool = True
    zero_init_head: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        x = DenseStack(self.dense_units, self.mlp_layers, self.eps, self.act, self.layer_norm)(x)
        init = uniform_init(0.0) if self.zero_init_head else trunc_normal_init
        return nn.Dense(self.bins, kernel_init=init)(x)


def resolve_actor_cls(actor_cfg) -> type:
    """``cfg.algo.actor.cls`` selects the actor class (reference
    agent.py:1136-1141 via ``hydra.utils.get_class``); exp overlays pick
    ``MinedojoActor`` for MineDojo.  Shared by the DV1/DV2/DV3 (and therefore
    P2E/JEPA) ``build_agent``s."""
    if not actor_cfg.get("cls"):
        return Actor
    from sheeprl_tpu.config import get_callable

    actor_cls = get_callable(actor_cfg.cls)
    if not (isinstance(actor_cls, type) and issubclass(actor_cls, Actor)):
        raise ValueError(f"algo.actor.cls must name an Actor subclass, got {actor_cfg.cls!r}")
    return actor_cls


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
    target_critic_state: Optional[Dict[str, Any]] = None,
):
    """Create module definitions + params (reference agent.py:935-1235).

    Returns ``(world_model_def, actor_def, critic_def, params)`` with params =
    {"world_model", "actor", "critic", "target_critic"}.
    """
    wm_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    eps = float(cfg.algo.mlp_layer_norm.kw.get("eps", 1e-3)) if cfg.algo.get("mlp_layer_norm") else 1e-3
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_decoder_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_decoder_keys = list(cfg.algo.mlp_keys.decoder)
    image_size = tuple(obs_space[cnn_keys[0]].shape[-2:]) if cnn_keys else (64, 64)
    cnn_stages = int(np.log2(cfg.env.screen_size) - np.log2(4)) if cnn_keys else 4
    recurrent_state_size = wm_cfg.recurrent_model.recurrent_state_size
    stochastic_size = wm_cfg.stochastic_size
    discrete_size = wm_cfg.discrete_size
    latent_state_size = stochastic_size * discrete_size + recurrent_state_size

    world_model_def = WorldModel(
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_decoder_keys=tuple(cnn_decoder_keys),
        mlp_decoder_keys=tuple(mlp_decoder_keys),
        mlp_output_dims=tuple(int(prod(obs_space[k].shape)) for k in mlp_decoder_keys),
        cnn_input_channels=tuple(int(prod(obs_space[k].shape[:-2])) for k in cnn_decoder_keys),
        image_size=image_size,
        channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        cnn_stages=cnn_stages,
        encoder_dense_units=wm_cfg.encoder.dense_units,
        encoder_mlp_layers=wm_cfg.encoder.mlp_layers,
        decoder_dense_units=wm_cfg.observation_model.dense_units,
        decoder_mlp_layers=wm_cfg.observation_model.mlp_layers,
        recurrent_state_size=recurrent_state_size,
        stochastic_size=stochastic_size,
        discrete_size=discrete_size,
        rssm_dense_units=wm_cfg.recurrent_model.dense_units,
        rssm_hidden_size=wm_cfg.representation_model.hidden_size,
        reward_dense_units=wm_cfg.reward_model.dense_units,
        reward_mlp_layers=wm_cfg.reward_model.mlp_layers,
        reward_bins=wm_cfg.reward_model.bins,
        continue_dense_units=wm_cfg.discount_model.dense_units,
        continue_mlp_layers=wm_cfg.discount_model.mlp_layers,
        unimix=cfg.algo.unimix,
        eps=eps,
        learnable_initial_recurrent_state=wm_cfg.learnable_initial_recurrent_state,
        decoupled_rssm=wm_cfg.decoupled_rssm,
        # Pallas fused LayerNorm-GRU: `algo.rssm_pallas` is the deploy-time
        # lever (bench.py mfu_levers sweeps it); the older
        # recurrent_model.fused_kernel spelling still works
        fused_gru=bool(
            cfg.algo.get("rssm_pallas", False)
            or wm_cfg.recurrent_model.get("fused_kernel", False)
        ),
    )
    actor_def = resolve_actor_cls(actor_cfg)(
        latent_state_size=latent_state_size,
        actions_dim=tuple(int(a) for a in actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.type,
        init_std=actor_cfg.init_std,
        min_std=actor_cfg.min_std,
        max_std=actor_cfg.get("max_std", 1.0),
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        unimix=cfg.algo.unimix,
        action_clip=actor_cfg.action_clip,
        eps=eps,
    )
    critic_def = Critic(
        dense_units=critic_cfg.dense_units, mlp_layers=critic_cfg.mlp_layers, bins=critic_cfg.bins, eps=eps
    )

    key = jax.random.PRNGKey(int(cfg.seed or 0))
    k_wm, k_actor, k_critic, k_call = jax.random.split(key, 4)
    n_envs = 1
    sample_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        sample_obs[k] = jnp.zeros((n_envs,) + tuple(obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((n_envs, int(prod(obs_space[k].shape))), jnp.float32)
    sample_action = jnp.zeros((n_envs, int(sum(actions_dim))), jnp.float32)
    sample_is_first = jnp.ones((n_envs, 1), jnp.float32)
    wm_params = world_model_def.init(k_wm, sample_obs, sample_action, sample_is_first, k_call)
    sample_latent = jnp.zeros((n_envs, latent_state_size), jnp.float32)
    actor_params = actor_def.init(k_actor, sample_latent)
    critic_params = critic_def.init(k_critic, sample_latent)
    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
    }
    if world_model_state is not None:
        params["world_model"] = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    if actor_state is not None:
        params["actor"] = jax.tree_util.tree_map(jnp.asarray, actor_state)
    if critic_state is not None:
        params["critic"] = jax.tree_util.tree_map(jnp.asarray, critic_state)
    if target_critic_state is not None:
        params["target_critic"] = jax.tree_util.tree_map(jnp.asarray, target_critic_state)
    return world_model_def, actor_def, critic_def, params


class PlayerDV3:
    """Stateful env-interaction wrapper (reference agent.py:596-691).

    Holds per-env recurrent/stochastic/action state as device arrays and
    steps them with one jitted graph per call; resets are mask-based (static
    shapes, no host round-trip per reset).
    """

    def __init__(self, world_model_def: WorldModel, actor_def: Actor, actions_dim, num_envs: int):
        self.world_model_def = world_model_def
        self.actor_def = actor_def
        self.actions_dim = actions_dim
        self.num_envs = num_envs
        self.state = None

        wm = world_model_def

        def _init_state(wm_params, n):
            h0, z0 = world_model_def.apply(wm_params, (n,), method="initial_states")
            return {
                "recurrent": h0,
                "stochastic": z0,
                "actions": jnp.zeros((n, int(sum(actions_dim))), jnp.float32),
            }

        def _reset_masked(wm_params, state, reset_mask):
            init = _init_state(wm_params, state["recurrent"].shape[0])
            return jax.tree_util.tree_map(
                lambda i, s: reset_mask * i + (1 - reset_mask) * s, init, state
            )

        def _step(wm_params, actor_params, state, obs, key, greedy, mask):
            k1, k2 = jax.random.split(key)
            embedded = wm.apply(wm_params, obs, method="encode")
            recurrent = wm.apply(
                wm_params, state["stochastic"], state["actions"], state["recurrent"], method="recurrent_step"
            )
            if wm.decoupled_rssm:
                _, stochastic = wm.apply(wm_params, None, embedded, k1, method="representation")
            else:
                _, stochastic = wm.apply(wm_params, recurrent, embedded, k1, method="representation")
            latent = jnp.concatenate([stochastic, recurrent], axis=-1)
            actions = actor_def.apply(actor_params, latent, k2, greedy, mask, method="act")
            new_state = {"recurrent": recurrent, "stochastic": stochastic, "actions": actions}
            return actions, new_state

        self._init_state = jax.jit(_init_state, static_argnums=(1,))
        self._reset_masked = jax.jit(_reset_masked)
        self._step = jax.jit(_step, static_argnums=(5,))

    def init_states(self, wm_params, reset_mask: Optional[np.ndarray] = None) -> None:
        """Full or masked state reset (reference agent.py:644-659).
        ``reset_mask`` is ``[num_envs, 1]`` float (1 = reset that env)."""
        if self.state is None or reset_mask is None:
            self.state = self._init_state(wm_params, self.num_envs)
        else:
            self.state = self._reset_masked(wm_params, self.state, jnp.asarray(reset_mask, jnp.float32))

    def get_actions(self, wm_params, actor_params, obs, key, greedy: bool = False, mask=None) -> jax.Array:
        """``mask`` (dict of ``mask_*`` arrays, or None) feeds the hierarchical
        action masking of ``MinedojoActor`` (reference dreamer_v3.py:614-617)."""
        actions, self.state = self._step(wm_params, actor_params, self.state, obs, key, greedy, mask)
        return actions
