"""DreamerV3 world-model loss (reference /root/reference/sheeprl/algos/dreamer_v3/loss.py:9-96).

Pure function over arrays; KL balancing (0.5 dynamic / 0.1 representation)
with free nats, observation/reward/continue log-probs.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    MSEDistribution,
    SymlogDistribution,
    TwoHotEncodingDistribution,
    kl_categorical,
)


def reconstruction_loss(
    po: Dict[str, object],
    observations: Dict[str, jax.Array],
    pr: TwoHotEncodingDistribution,
    rewards: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    kl_dynamic: float = 0.5,
    kl_representation: float = 0.1,
    kl_free_nats: float = 1.0,
    kl_regularizer: float = 1.0,
    pc: Optional[Bernoulli] = None,
    continue_targets: Optional[jax.Array] = None,
    continue_scale_factor: float = 1.0,
) -> Tuple[jax.Array, ...]:
    """priors/posteriors logits are ``[T, B, stoch, discrete]``."""
    if len(po) == 0:
        observation_loss = jnp.zeros_like(rewards[..., 0])
    else:
        observation_loss = -sum(po[k].log_prob(observations[k]) for k in po.keys())
    reward_loss = -pr.log_prob(rewards)
    # KL balancing (reference loss.py:70-83)
    dyn_loss = kl = kl_categorical(
        jax.lax.stop_gradient(posteriors_logits), priors_logits, event_dims=1
    )
    free_nats = jnp.full_like(dyn_loss, kl_free_nats)
    dyn_loss = kl_dynamic * jnp.maximum(dyn_loss, free_nats)
    repr_loss = kl_categorical(
        posteriors_logits, jax.lax.stop_gradient(priors_logits), event_dims=1
    )
    repr_loss = kl_representation * jnp.maximum(repr_loss, free_nats)
    kl_loss = dyn_loss + repr_loss
    if pc is not None and continue_targets is not None:
        continue_loss = continue_scale_factor * -pc.log_prob(continue_targets)
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    rec_loss = jnp.mean(kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss)
    return (
        rec_loss,
        jnp.mean(kl),
        jnp.mean(kl_loss),
        jnp.mean(reward_loss),
        jnp.mean(observation_loss),
        jnp.mean(continue_loss),
    )
