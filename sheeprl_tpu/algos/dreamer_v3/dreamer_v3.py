"""DreamerV3 training loop — TPU-native re-design of
/root/reference/sheeprl/algos/dreamer_v3/dreamer_v3.py:48-830.

The reference's two hot loops are Python ``for`` loops over GRU cells
(dynamic learning over T≈64 steps, dreamer_v3.py:134-145; imagination over
H=15, :235-241).  Here each gradient step is ONE jitted XLA graph:

- dynamic learning = `lax.scan` over the sequence axis;
- imagination = `lax.scan` over the horizon **inside the actor loss**, so
  gradients flow through the dynamics for continuous control exactly as the
  reference's autograd tape does;
- the three optimizer updates (world/actor/critic), the Moments percentile
  EMA and the target-critic Polyak update all live in the same graph;
- data-parallelism is `shard_map` over the 1-D ``"data"`` mesh axis: the
  batch enters sharded ``P(None, "data")`` (time × **sharded batch**), params
  replicated; the three gradient pytrees are explicitly `lax.pmean`-reduced
  before their optimizer updates and the Moments quantile runs on the
  `lax.all_gather`-ed lambda values (reference `fabric.all_gather` in
  Moments, utils.py:56-64).  Per-device batch math: each device computes
  ``per_rank_batch_size`` of the staged ``per_rank_batch_size * world_size``
  sequences, so adding devices scales global batch exactly like reference
  DDP ranks.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Sequence

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_agent
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import (  # noqa: F401
    AGGREGATOR_KEYS,
    MODELS_TO_REGISTER,
    chunked_dynamic_scan,
    init_moments_state,
    prepare_obs,
    rssm_scan_spec,
    test,
    update_moments,
)
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.factory import make_dreamer_replay_buffer
from sheeprl_tpu.diagnostics.health import mean_stats
from sheeprl_tpu.data.slab import rssm_state_slab, step_slab
from sheeprl_tpu.envs.env import make_env_fns, pipelined_vector_env
from sheeprl_tpu.envs.player import fetch_values, obs_sharding
from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    MSEDistribution,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.ops.numerics import compute_lambda_values
from sheeprl_tpu.parallel.dp import P, batch_spec, dp_axis, dp_jit, fold_key, pmean_tree, train_batches, local_sample_size
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import DeviceMetricsDrain, MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, get_diagnostics, save_configs


def make_train_step(
    world_model_def,
    actor_def,
    critic_def,
    optimizers,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    mesh=None,
):
    """Build the jitted single-gradient-step update.

    Signature: (params, opt_states, moments_state, batch, key, tau) ->
    (params, opt_states, moments_state, metrics_vec).
    ``batch`` leaves are [T, B, ...] float arrays (pixels already in [-0.5, .5]).
    With a >1-device ``mesh`` the step is shard_map'd: B is sharded over
    ``"data"``, grads pmean'd, Moments quantiles all-gathered.
    """
    axis = dp_axis(mesh)
    cdt = compute_dtype_of(cfg)  # bf16 under fabric.precision=bf16-*
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size
    recurrent_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    # lax.scan unroll factor for the RSSM/imagination loops: unrolling
    # amortizes per-iteration scan overhead (one S-size sweep on v5e showed
    # ~6% at unroll=8, and the interleaved A/B harness — tools/perf_study.py
    # measure_unroll_ab — is how to (re)confirm it on a given chip; PERF.md
    # §4) at the cost of ~unroll x longer compiles, so it defaults to 1 and
    # is a deploy-time knob.  Caveat: cost_analysis() FLOPs inflate under
    # unrolling, so compare step_ms — the telemetry_cost journal event
    # carries this caveat (cost_note) whenever unroll > 1.
    scan_unroll = int(cfg.algo.get("scan_unroll", 1))
    # chunked sequence-parallel RSSM scan (PERF.md §4): split the T-step
    # dynamic-learning scan into K chunks seeded from replay-stored states
    # and fold the chunk axis into the batch axis — the GRU GEMM then runs at
    # B*K rows.  rssm_chunks=1 is bit-identical to the sequential scan.
    rssm_chunks, rssm_burn_in = rssm_scan_spec(cfg)
    gamma = cfg.algo.gamma
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)

    from sheeprl_tpu.diagnostics.health import health_spec, health_stats
    from sheeprl_tpu.diagnostics.sentinel import select_finite, sentinel_spec

    sentinel = sentinel_spec(cfg)
    health = health_spec(cfg)

    def train_step(params, opt_states, moments_state, batch, key, tau):
        T, B = batch["actions"].shape[:2]
        key = fold_key(key, axis)
        k_wm, k_img, k_img_actions = jax.random.split(key, 3)

        # sentinel snapshots: the skip_update guard at the end reverts to
        # these when the step's metric vector — which includes every loss and
        # grad norm — goes non-finite.  tree_map rebuilds every container
        # (leaves shared) so nested in-place mutation can never alias the
        # snapshot
        if sentinel.skip_update:
            copy = lambda tree: jax.tree_util.tree_map(lambda leaf: leaf, tree)  # noqa: E731
            prev_state = (copy(params), copy(opt_states), moments_state)

        # --- target critic Polyak update (reference dreamer_v3.py:713-720) --
        params["target_critic"] = jax.tree_util.tree_map(
            lambda c, t: tau * c + (1 - tau) * t, params["critic"], params["target_critic"]
        )

        # loss-side targets stay fp32; the compute path runs in `cdt` via the
        # JMP-style casts at each loss entry (params + inputs -> cdt, flax
        # promotes, distributions upcast back to fp32 at the loss boundary)
        target_obs = {k: batch[k] for k in set(cnn_dec_keys + mlp_dec_keys)}  # fp32 targets
        batch_obs = cast_floating(target_obs, cdt)  # network input
        # shift actions right by one: a_0 = 0 (reference dreamer_v3.py:104-105)
        batch_actions = jnp.concatenate(
            [jnp.zeros_like(batch["actions"][:1]), batch["actions"][:-1]], axis=0
        ).astype(cdt)
        is_first = batch["is_first"].at[0].set(1.0).astype(cdt)

        # ---------------- DYNAMIC LEARNING ---------------------------------
        def wm_loss_fn(wm_params):
            wm_params = cast_floating(wm_params, cdt)
            embedded = world_model_def.apply(wm_params, batch_obs, method="encode")

            def scan_body(carry, x):
                posterior, recurrent = carry
                action_t, embed_t, is_first_t, key_t = x
                recurrent, posterior, _, post_logits, prior_logits = world_model_def.apply(
                    wm_params, posterior, recurrent, action_t, embed_t, is_first_t, key_t, method="dynamic"
                )
                return (posterior, recurrent), (recurrent, posterior, post_logits, prior_logits)

            recurrents, posteriors, post_logits, prior_logits = chunked_dynamic_scan(
                scan_body,
                batch_actions,
                embedded,
                is_first,
                k_wm,
                stoch_flat=stoch_flat,
                recurrent_size=recurrent_size,
                cdt=cdt,
                chunks=rssm_chunks,
                burn_in=rssm_burn_in,
                stored_recurrent=batch.get("rssm_recurrent"),
                stored_posterior=batch.get("rssm_posterior"),
                stored_valid=batch.get("rssm_valid"),
                unroll=scan_unroll,
            )
            latents = jnp.concatenate([posteriors, recurrents], axis=-1)
            recon = world_model_def.apply(wm_params, latents, method="decode")
            po = {k: MSEDistribution(recon[k], dims=len(recon[k].shape[2:])) for k in cnn_dec_keys}
            po.update(
                {k: SymlogDistribution(recon[k], dims=len(recon[k].shape[2:])) for k in mlp_dec_keys}
            )
            pr = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, latents, method="reward_logits"), dims=1
            )
            pc = Bernoulli(
                world_model_def.apply(wm_params, latents, method="continue_logits"), event_dims=1
            )
            continues_targets = 1 - batch["terminated"]
            pl = prior_logits.reshape(T, B, wm_cfg.stochastic_size, wm_cfg.discrete_size)
            ql = post_logits.reshape(T, B, wm_cfg.stochastic_size, wm_cfg.discrete_size)
            rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po,
                target_obs,
                pr,
                batch["rewards"],
                pl,
                ql,
                wm_cfg.kl_dynamic,
                wm_cfg.kl_representation,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                pc,
                continues_targets,
                wm_cfg.continue_scale_factor,
            )
            aux = {
                "posteriors": posteriors,
                "recurrents": recurrents,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
                "post_logits": ql,
                "prior_logits": pl,
            }
            return rec_loss, aux

        (rec_loss, aux), wm_grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(params["world_model"])
        wm_grads = pmean_tree(wm_grads, axis)
        wm_updates, opt_states["world_model"] = optimizers["world_model"].update(
            wm_grads, opt_states["world_model"], params["world_model"]
        )
        params["world_model"] = optax.apply_updates(params["world_model"], wm_updates)

        # ---------------- BEHAVIOUR LEARNING -------------------------------
        # (uses the freshly updated world model, like the reference)
        wm_params = cast_floating(params["world_model"], cdt)
        posteriors = jax.lax.stop_gradient(aux["posteriors"]).reshape(T * B, stoch_flat)
        recurrents = jax.lax.stop_gradient(aux["recurrents"]).reshape(T * B, recurrent_size)
        true_continue = (1 - batch["terminated"]).reshape(T * B, 1)

        def actor_loss_fn(actor_params, moments_state):
            actor_params = cast_floating(actor_params, cdt)
            latent0 = jnp.concatenate([posteriors, recurrents], axis=-1)
            a0 = actor_def.apply(actor_params, jax.lax.stop_gradient(latent0), k_img_actions, False, method="act")

            def img_body(carry, key_t):
                prior, recurrent, actions = carry
                k_dyn, k_act = jax.random.split(key_t)
                prior, recurrent = world_model_def.apply(
                    wm_params, prior, recurrent, actions, k_dyn, method="imagination"
                )
                latent = jnp.concatenate([prior, recurrent], axis=-1)
                actions = actor_def.apply(
                    actor_params, jax.lax.stop_gradient(latent), k_act, False, method="act"
                )
                return (prior, recurrent, actions), (latent, actions)

            keys_h = jax.random.split(k_img, horizon)
            _, (latents_h, actions_h) = jax.lax.scan(img_body, (posteriors, recurrents, a0), keys_h, unroll=scan_unroll)
            imagined_trajectories = jnp.concatenate([latent0[None], latents_h], axis=0)  # [H+1, TB, L]
            imagined_actions = jnp.concatenate([a0[None], actions_h], axis=0)

            predicted_values = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(params["critic"], cdt), imagined_trajectories), dims=1
            ).mean
            predicted_rewards = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, imagined_trajectories, method="reward_logits"), dims=1
            ).mean
            continues = Bernoulli(
                world_model_def.apply(wm_params, imagined_trajectories, method="continue_logits"),
                event_dims=1,
            ).mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)

            lambda_values = compute_lambda_values(
                predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda=cfg.algo.lmbda
            )
            discount = jnp.cumprod(continues * gamma, axis=0) / gamma
            discount = jax.lax.stop_gradient(discount)

            baseline = predicted_values[:-1]
            offset, invscale, new_moments = update_moments(
                moments_state,
                lambda_values,
                cfg.algo.actor.moments.decay,
                cfg.algo.actor.moments.max,
                cfg.algo.actor.moments.percentile.low,
                cfg.algo.actor.moments.percentile.high,
                axis_name=axis,
            )
            normed_lambda_values = (lambda_values - offset) / invscale
            normed_baseline = (baseline - offset) / invscale
            advantage = normed_lambda_values - normed_baseline
            log_probs, entropies = actor_def.apply(
                actor_params,
                jax.lax.stop_gradient(imagined_trajectories),
                jax.lax.stop_gradient(imagined_actions),
                method="log_prob_entropy",
            )
            if is_continuous:
                objective = advantage
            else:
                objective = log_probs[:-1] * jax.lax.stop_gradient(advantage)
            entropy = cfg.algo.actor.ent_coef * entropies
            policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[:-1]))
            aux2 = {
                "imagined_trajectories": jax.lax.stop_gradient(imagined_trajectories),
                "lambda_values": jax.lax.stop_gradient(lambda_values),
                "discount": discount,
                "moments": new_moments,
            }
            return policy_loss, aux2

        (policy_loss, aux2), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            params["actor"], moments_state
        )
        actor_grads = pmean_tree(actor_grads, axis)
        actor_updates, opt_states["actor"] = optimizers["actor"].update(
            actor_grads, opt_states["actor"], params["actor"]
        )
        params["actor"] = optax.apply_updates(params["actor"], actor_updates)
        moments_state = aux2["moments"]

        # ---------------- CRITIC LEARNING ----------------------------------
        imagined_trajectories = aux2["imagined_trajectories"]
        lambda_values = aux2["lambda_values"]
        discount = aux2["discount"]

        def critic_loss_fn(critic_params):
            qv = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(critic_params, cdt), imagined_trajectories[:-1]), dims=1
            )
            predicted_target_values = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(params["target_critic"], cdt), imagined_trajectories[:-1]),
                dims=1,
            ).mean
            value_loss = -qv.log_prob(lambda_values)
            value_loss = value_loss - qv.log_prob(jax.lax.stop_gradient(predicted_target_values))
            return jnp.mean(value_loss * discount[:-1, ..., 0])

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_grads = pmean_tree(critic_grads, axis)
        critic_updates, opt_states["critic"] = optimizers["critic"].update(
            critic_grads, opt_states["critic"], params["critic"]
        )
        params["critic"] = optax.apply_updates(params["critic"], critic_updates)

        metrics = jnp.stack(
            [
                rec_loss,
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                policy_loss,
                value_loss,
                optax.global_norm(wm_grads),
                optax.global_norm(actor_grads),
                optax.global_norm(critic_grads),
            ]
        )
        metrics = pmean_tree(metrics, axis)
        # learn-health stats over the three module trees: the grads are
        # already pmean'd and updates/params are replicated, so the dict is
        # identical on every device and rides the metric drain's batched
        # fetch (zero extra syncs; {} when diagnostics.health is off)
        if health.enabled:
            hstats = health_stats(
                {"world_model": wm_grads, "actor": actor_grads, "critic": critic_grads},
                {"world_model": wm_updates, "actor": actor_updates, "critic": critic_updates},
                {"world_model": params["world_model"], "actor": params["actor"], "critic": params["critic"]},
                per_module=health.per_module,
                dead_eps=health.dead_eps,
            )
        else:
            hstats = {}
        if sentinel.skip_update:
            finite = jnp.all(jnp.isfinite(metrics))
            params, opt_states, moments_state = select_finite(
                finite, (params, opt_states, moments_state), prev_state
            )
        return params, opt_states, moments_state, metrics, hstats

    from sheeprl_tpu.parallel.dp import fsdp_min_shard_bytes

    return dp_jit(
        train_step,
        mesh,
        in_specs=(P(), P(), P(), batch_spec(batch_axis=1), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        donate_argnums=(0, 1, 2),
        min_shard_bytes=fsdp_min_shard_bytes(cfg),
    )


METRIC_ORDER = [
    "Loss/world_model_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "Loss/policy_loss",
    "Loss/value_loss",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
]


def _build_agent_from_state(runtime, actions_dim, is_continuous, cfg, obs_space, state):
    return build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        state["world_model"] if state else None,
        state["actor"] if state else None,
        state["critic"] if state else None,
        state["target_critic"] if state else None,
    )


@register_algorithm()
def main(runtime, cfg):
    return _dreamer_main(runtime, cfg, _build_agent_from_state, make_train_step)


def _default_make_optimizers(cfg, params, agent_state, extra_opt_setup=None):
    """DV3's three optimizers (world/actor/critic) with generic restore."""
    optimizers = {
        "world_model": optax.chain(
            optax.clip_by_global_norm(cfg.algo.world_model.clip_gradients),
            instantiate(cfg.algo.world_model.optimizer),
        ),
        "actor": optax.chain(
            optax.clip_by_global_norm(cfg.algo.actor.clip_gradients),
            instantiate(cfg.algo.actor.optimizer),
        ),
        "critic": optax.chain(
            optax.clip_by_global_norm(cfg.algo.critic.clip_gradients),
            instantiate(cfg.algo.critic.optimizer),
        ),
    }
    opt_states = {
        "world_model": optimizers["world_model"].init(params["world_model"]),
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
    }
    if extra_opt_setup is not None:
        opt_states = extra_opt_setup(optimizers, opt_states, params)
    if agent_state and "opt_states" in agent_state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            agent_state["opt_states"],
        )
    return optimizers, opt_states


def _dreamer_main(
    runtime,
    cfg,
    build_agent_fn,
    make_train_step_fn,
    extra_opt_setup=None,
    *,
    make_optimizers_fn=None,
    init_moments_fn=None,
    player_actor_fn=None,
    metric_order=None,
    final_test_fn=None,
    load_agent_state_fn=None,
    player_cls=PlayerDV3,
):
    """Shared Dreamer-family training engine.

    The DV3/DV1-style loop (env interaction + sequential replay + jitted
    train step + checkpoint) parameterized by hooks so the JEPA variant and
    the Plan2Explore exploration/finetuning entrypoints reuse it:

    - ``build_agent_fn(runtime, actions_dim, is_continuous, cfg, obs_space,
      agent_state)`` -> ``(wm_def, actor_def, critic_def, params)`` — params
      may carry extra keys (JEPA heads, P2E ensembles/critics); every key is
      checkpointed.
    - ``make_optimizers_fn(cfg, params, agent_state)`` -> ``(optimizers,
      opt_states)``; default = DV3's world/actor/critic trio.
    - ``init_moments_fn(cfg, agent_state)`` -> Moments pytree (P2E: a dict of
      task + per-exploration-critic states).
    - ``player_actor_fn(params, has_trained)`` -> actor params for env
      interaction (P2E exploration plays with ``actor_exploration``;
      finetuning switches exploration -> task at the first gradient step,
      reference p2e_dv3_finetuning.py:350-354).
    - ``final_test_fn(player, params, runtime, cfg, log_dir)`` -> reward
      (P2E: zero-shot test with the task actor).
    - ``load_agent_state_fn(runtime, cfg)`` -> state used to *initialize*
      models when not resuming (finetuning loads the exploration checkpoint,
      reference cli.py:117-148); counters/buffers restore only from
      ``checkpoint.resume_from``.
    """
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    agent_state = state
    if agent_state is None and load_agent_state_fn is not None:
        agent_state = load_agent_state_fn(runtime, cfg)

    cfg.env.frame_stack = -1
    if 2 ** int(np.log2(cfg.env.screen_size)) != cfg.env.screen_size:
        raise ValueError(f"The screen size must be a power of 2, got: {cfg.env.screen_size}")

    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    rng_key = runtime.seed_everything(cfg.seed)

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    action_space = envs.single_action_space
    observation_space = envs.single_observation_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    has_decoders = len(cfg.algo.cnn_keys.decoder) + len(cfg.algo.mlp_keys.decoder) > 0
    if has_decoders and (
        len(set(cfg.algo.cnn_keys.encoder).intersection(set(cfg.algo.cnn_keys.decoder))) == 0
        and len(set(cfg.algo.mlp_keys.encoder).intersection(set(cfg.algo.mlp_keys.decoder))) == 0
    ):
        raise RuntimeError("The CNN keys or the MLP keys of the encoder and decoder must not be disjointed")
    obs_keys = list(cfg.algo.cnn_keys.encoder) + list(cfg.algo.mlp_keys.encoder)

    world_model_def, actor_def, critic_def, params = build_agent_fn(
        runtime, actions_dim, is_continuous, cfg, observation_space, agent_state
    )
    # bf16-true stores the weights themselves in bf16; *-mixed keeps fp32
    # master weights and casts per-loss inside the train step
    params = cast_floating(params, runtime.param_dtype)
    player = player_cls(world_model_def, actor_def, actions_dim, num_envs)

    if make_optimizers_fn is None:
        optimizers, opt_states = _default_make_optimizers(cfg, params, agent_state, extra_opt_setup)
    else:
        optimizers, opt_states = make_optimizers_fn(cfg, params, agent_state)
    if init_moments_fn is None:
        moments_state = init_moments_state()
        if agent_state and "moments" in agent_state:
            moments_state = jax.tree_util.tree_map(jnp.asarray, agent_state["moments"])
    else:
        moments_state = init_moments_fn(cfg, agent_state)
    if player_actor_fn is None:
        player_actor_fn = lambda p, has_trained: p["actor"]  # noqa: E731
    if metric_order is None:
        metric_order = METRIC_ORDER

    from sheeprl_tpu.parallel.dp import fsdp_min_shard_bytes
    from sheeprl_tpu.parallel.fsdp import fsdp_active, shard_map_summary, shard_tree
    from sheeprl_tpu.parallel.mesh import replicated_sharding

    if world_size > 1:
        if fsdp_active(runtime.mesh):
            # FSDP placement (howto/sharding.md): large leaves land sliced
            # over the "model" axis, small leaves replicated — the committed
            # shardings are what the global-view jit propagates from.  The
            # Moments state is a handful of scalars: always replicated.
            min_bytes = fsdp_min_shard_bytes(cfg)
            params = shard_tree(params, runtime.mesh, min_bytes)
            opt_states = shard_tree(opt_states, runtime.mesh, min_bytes)
            moments_state = jax.device_put(moments_state, replicated_sharding(runtime.mesh))
            diag.on_fsdp_shard_map(
                shard_map_summary(
                    {"params": params, "opt_state": opt_states}, runtime.mesh, min_bytes
                )
            )
        else:
            params = jax.device_put(params, replicated_sharding(runtime.mesh))
            opt_states = jax.device_put(opt_states, replicated_sharding(runtime.mesh))
            moments_state = jax.device_put(moments_state, replicated_sharding(runtime.mesh))

    # telemetry instrumentation (shared engine: dv3 / jepa / p2e inherit):
    # recompile watchdog + exact compiled-step FLOPs for the live MFU gauge.
    # The player forward stays uninstrumented — its compiles are still counted
    # by the process-wide jax.monitoring listener.
    loop_scan_unroll = int(cfg.algo.get("scan_unroll", 1) or 1)
    train_step = diag.instrument(
        "train_step",
        make_train_step_fn(
            world_model_def,
            actor_def,
            critic_def,
            optimizers,
            cfg,
            actions_dim,
            is_continuous,
            mesh=runtime.mesh if world_size > 1 else None,
        ),
        kind="train",
        donate_argnums=(0, 1, 2),  # params, opt_states, moments — audited at first dispatch
        # unrolled scans inflate cost_analysis() FLOPs (PERF.md §4), which
        # would silently inflate Telemetry/mfu too — the telemetry_cost
        # journal event carries this caveat so MFU readers know to compare
        # step_ms instead
        cost_note=(
            f"cost_analysis FLOPs inflate under scan unrolling (scan_unroll={loop_scan_unroll}); "
            "compare step_ms, not MFU"
            if loop_scan_unroll > 1
            else None
        ),
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)
    diag.register_footprint("moments", moments_state)
    # one staged h2d per vector step for the player's obs slab (see
    # envs/player.py); the action fetch below is the one blocking d2h
    stage_sharding = obs_sharding(runtime.mesh if world_size > 1 else None)

    buffer_size = cfg.buffer.size // num_envs if not cfg.dry_run else 2
    # HBM-resident replay when buffer.device=True: frames never leave the
    # device after collection (sheeprl_tpu/data/device_buffer.py) — removes
    # the ~B*T*H*W*C bytes of host->HBM traffic per gradient step
    rb, use_device_buffer = make_dreamer_replay_buffer(
        cfg, world_size, num_envs, obs_keys, log_dir, buffer_size, mesh=runtime.mesh
    )
    diag.track_buffer("replay", rb)
    buffer_state = state
    if buffer_state is None and cfg.buffer.get("load_from_exploration") and agent_state:
        # P2E finetuning may continue on the exploration replay buffer
        # (reference p2e_dv3_finetuning.py:188-195)
        buffer_state = agent_state
    if (
        buffer_state
        and (cfg.buffer.checkpoint or cfg.buffer.get("load_from_exploration"))
        and buffer_state.get("rb") is not None
    ):
        rb.load_state_dict(buffer_state["rb"])
        if rssm_scan_spec(cfg)[0] > 1:
            # a replay collected WITHOUT the chunked scan has no stored-state
            # rows — fail with the cause here instead of a generic
            # unknown-buffer-key error at the first add
            loaded = getattr(rb, "buffer", None)
            if isinstance(loaded, (list, tuple)) and loaded:
                loaded = loaded[0]
            loaded_keys = set(loaded.buffer if hasattr(loaded, "buffer") else loaded or {})
            if loaded_keys and "rssm_recurrent" not in loaded_keys:
                raise ValueError(
                    "algo.rssm_chunks > 1 needs replay rows carrying the player's RSSM "
                    "state (rssm_recurrent/rssm_posterior/rssm_valid), but the restored "
                    "buffer was collected without them — resume with rssm_chunks=1 or "
                    "start a fresh buffer"
                )

    train_step_count = 0
    last_train = 0
    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["iter_num"] * num_envs if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if cfg.checkpoint.resume_from:
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    # ---- first obs (reference dreamer_v3.py:578-589) ----------------------
    obs = envs.reset(seed=cfg.seed)[0]
    step_data: Dict[str, np.ndarray] = step_slab(num_envs, {k: obs[k] for k in obs_keys})
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones_like(step_data["terminated"])
    player.init_states(params["world_model"])

    # chunked-scan stored states (algo.rssm_chunks > 1): every replay row
    # additionally carries the player's post-step RSSM state so the train
    # step can seed chunk boundaries from it (rssm_valid=0 on rows written
    # without one — prefill, bookkeeping — falls back to the learned initial
    # state).  Costs H+Z floats per step per env in replay and rides the
    # iteration's ONE blocking d2h on the host-buffer path.
    store_rssm_state = rssm_scan_spec(cfg)[0] > 1
    if store_rssm_state:
        rssm_zero_recurrent = np.zeros(
            (num_envs, int(player.state["recurrent"].shape[-1])), np.float32
        )
        rssm_zero_stochastic = np.zeros(
            (num_envs, int(player.state["stochastic"].shape[-1])), np.float32
        )
        step_data.update(
            rssm_state_slab(num_envs, rssm_zero_recurrent, rssm_zero_stochastic, valid=False)
        )

    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cumulative_grad_steps = 0
    has_trained = bool(cfg.checkpoint.resume_from)

    def split_real_actions(actions: np.ndarray) -> np.ndarray:
        if is_continuous:
            return actions.reshape(num_envs, -1)
        idxs = []
        start = 0
        for d in actions_dim:
            idxs.append(np.argmax(actions[..., start : start + d], axis=-1))
            start += d
        return np.stack(idxs, axis=-1)

    metrics_drain = DeviceMetricsDrain()

    for iter_num in range(start_iter, total_iters + 1):
        policy_step_count += policy_steps_per_iter
        diag.note_env_steps(num_envs)

        # ---- policy forward + env dispatch + replay write -----------------
        # Split-phase iteration: the player forward is dispatched, its action
        # values are fetched, and `step_async` is issued THE MOMENT the
        # values land — the env workers then step
        # concurrently with everything below: the replay write, the sampling
        # + dispatch of this iteration's gradient steps, and the device
        # executing them.  Only `step_wait` (after the train dispatch) blocks
        # on the envs, so the per-iteration critical path is
        # ``fwd + fetch + max(train dispatch, env_step)`` instead of the
        # reference hot loop's full serialization (dreamer_v3.py:637-672).
        # Ordering tradeoff: the gradient-step dispatch (~ms of host work)
        # can hide behind either the action fetch (the pre-pipeline order) or
        # the env step (this order) but not both — the fetch's tunnel copy is
        # started at the same point either way, so the swing is only the host
        # dispatch time, and this order wins whenever env_step exceeds it
        # (every real simulator; bench.py's env_overlap pair measures it).
        with timer("Time/env_interaction_time"), diag.span("rollout"):
            actions_jnp = None
            if iter_num <= learning_starts and not cfg.checkpoint.resume_from:
                real_actions = actions = np.asarray(envs.action_space.sample())
                if not is_continuous:
                    actions = np.concatenate(
                        [
                            np.eye(act_dim, dtype=np.float32)[act]
                            for act, act_dim in zip(actions.reshape(len(actions_dim), -1), actions_dim)
                        ],
                        axis=-1,
                    )
                step_data["actions"] = actions.reshape(1, num_envs, -1)
                if store_rssm_state:
                    # prefill rows: the player never ran, so no state exists —
                    # valid=0 makes chunk starts here reset to the learned
                    # initial state instead of training on zeros
                    step_data.update(
                        rssm_state_slab(
                            num_envs, rssm_zero_recurrent, rssm_zero_stochastic, valid=False
                        )
                    )
            else:
                rng_key, step_key = jax.random.split(rng_key)
                torch_obs = prepare_obs(
                    obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs, sharding=stage_sharding
                )
                # mask_* observation keys feed MinedojoActor's hierarchical
                # action masking (reference dreamer_v3.py:614-617)
                mask = {k: v for k, v in torch_obs.items() if k.startswith("mask")} or None
                actions_jnp = player.get_actions(
                    params["world_model"], player_actor_fn(params, has_trained), torch_obs, step_key,
                    mask=mask,
                )
                if use_device_buffer:
                    # device-resident actions go straight into the HBM ring
                    # (no fetch needed for the write); the chunked-scan state
                    # record stays on device with them
                    step_data["actions"] = jnp.reshape(actions_jnp, (1, num_envs, -1))
                    if store_rssm_state:
                        step_data.update(
                            rssm_state_slab(
                                num_envs,
                                player.state["recurrent"],
                                player.state["stochastic"],
                                valid=True,
                            )
                        )
                    rb.add(step_data, validate_args=cfg.buffer.validate_args)
                diag.note_fetch()  # the iteration's ONE blocking d2h
                if store_rssm_state and not use_device_buffer:
                    # the stored states ride the SAME blocking fetch as the
                    # action values — still one d2h round trip per vector step
                    actions, host_recurrent, host_stochastic = fetch_values(
                        actions_jnp, player.state["recurrent"], player.state["stochastic"]
                    )
                    step_data.update(
                        rssm_state_slab(num_envs, host_recurrent, host_stochastic, valid=True)
                    )
                else:
                    actions = np.asarray(actions_jnp)  # blocking value fetch
                real_actions = split_real_actions(actions)
                if not use_device_buffer:
                    step_data["actions"] = actions.reshape(1, num_envs, -1)
            with diag.span("env_step_async"):
                envs.step_async(real_actions.reshape(envs.action_space.shape))
            if actions_jnp is None or not use_device_buffer:
                # prefill / host-buffer write — overlaps the env workers
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

        # ---- dispatch this iteration's gradient steps ---------------------
        # Runs while the env workers are stepping.  The sample includes
        # everything up to and including the current policy step (both buffer
        # modes — the add above always precedes the sampling); episode-end
        # bookkeeping rows from *this* step (known only at `step_wait`)
        # become sampleable one iteration later.  Likewise the
        # restart_on_exception truncation surgery (below) lands only after
        # these gradient steps have sampled, so a crashed-env discontinuity
        # can be trained on once as a normal transition — rare and bounded to
        # one iteration (the reference patches before training; we accept the
        # lag as the price of the overlap).
        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(
                (policy_step_count - prefill_steps * policy_steps_per_iter)
            )
            if cfg.dry_run:
                per_rank_gradient_steps = 1
            if per_rank_gradient_steps > 0:
                has_trained = True
                with diag.span("buffer-sample"):
                    local_data = rb.sample(
                        local_sample_size(cfg.algo.per_rank_batch_size * world_size, use_device_buffer),
                        sequence_length=cfg.algo.per_rank_sequence_length,
                        n_samples=per_rank_gradient_steps,
                    )
                    batches = train_batches(
                        local_data,
                        per_rank_gradient_steps,
                        runtime.mesh if world_size > 1 else None,
                        cnn_keys,
                        use_device_buffer,
                    )

                with timer("Time/train_time"), diag.span("train"):
                    for batch in batches:
                        batch = diag.maybe_inject_nan(iter_num, batch)
                        target_freq = cfg.algo.critic.get("per_rank_target_network_update_freq", 0)
                        if target_freq and cumulative_grad_steps % target_freq == 0:
                            tau = 1.0 if cumulative_grad_steps == 0 else cfg.algo.critic.get("tau", 1.0)
                        else:
                            tau = 0.0
                        rng_key, train_key = jax.random.split(rng_key)
                        out = train_step(
                            params, opt_states, moments_state, batch, train_key, jnp.float32(tau)
                        )
                        # P2E's step builders return 4 outputs (no health
                        # tree); the DV3/JEPA steps return 5 ({} when
                        # diagnostics.health is off)
                        params, opt_states, moments_state, metrics = out[:4]
                        step_health = out[4] if len(out) > 4 else None
                        cumulative_grad_steps += 1
                    train_step_count += 1
                metrics_drain.append(metrics, extra=step_health)

        # ---- collect the env step results (device keeps training) --------
        with timer("Time/env_interaction_time"), diag.span("env_wait"):
            next_obs, rewards, terminated, truncated, infos = envs.step_wait()
            dones = np.logical_or(terminated, truncated).astype(np.uint8)

        step_data["is_first"] = np.zeros_like(step_data["terminated"])
        if "restart_on_exception" in infos:
            for i, agent_roe in enumerate(infos["restart_on_exception"]):
                if agent_roe and not dones[i]:
                    if use_device_buffer:
                        rb.mark_last_truncated(i)
                    else:
                        sub = rb.buffer[i]
                        last_idx = (sub._pos - 1) % sub.buffer_size
                        sub["terminated"][last_idx] = np.zeros_like(sub["terminated"][last_idx])
                        sub["truncated"][last_idx] = np.ones_like(sub["truncated"][last_idx])
                        sub["is_first"][last_idx] = np.zeros_like(sub["is_first"][last_idx])
                    step_data["is_first"][0, i] = np.ones_like(step_data["is_first"][0, i])

        if "final_info" in infos and "episode" in infos["final_info"]:
            ep = infos["final_info"]["episode"]
            mask = ep.get("_r", infos["final_info"].get("_episode"))
            if mask is not None and np.any(mask):
                for r, l in zip(ep["r"][mask], ep["l"][mask]):
                    aggregator.update("Rewards/rew_avg", float(r))
                    aggregator.update("Game/ep_len_avg", float(l))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in obs_keys}
        if "final_obs" in infos:
            for idx, final_obs in enumerate(infos["final_obs"]):
                if final_obs is not None:
                    for k in obs_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs[k])

        step_data.update(
            step_slab(
                num_envs,
                {
                    **{k: next_obs[k] for k in obs_keys},
                    "terminated": terminated,
                    "truncated": truncated,
                    "rewards": rewards,
                },
                dtypes={"terminated": np.float32, "truncated": np.float32, "rewards": np.float32},
            )
        )
        obs = next_obs
        if cfg.env.clip_rewards:
            step_data["rewards"] = np.tanh(step_data["rewards"])

        dones_idxes = dones.nonzero()[0].tolist()
        if dones_idxes:
            reset_data = {}
            for k in obs_keys:
                reset_data[k] = real_next_obs[k][dones_idxes][np.newaxis]
            reset_data["terminated"] = step_data["terminated"][:, dones_idxes]
            reset_data["truncated"] = step_data["truncated"][:, dones_idxes]
            reset_data["actions"] = np.zeros((1, len(dones_idxes), int(sum(actions_dim))), np.float32)
            reset_data["rewards"] = step_data["rewards"][:, dones_idxes]
            reset_data["is_first"] = np.zeros_like(reset_data["terminated"])
            if store_rssm_state:
                # episode-end bookkeeping rows carry no player state (the env
                # just reset); valid=0 keeps chunk starts off them
                reset_data.update(
                    rssm_state_slab(
                        len(dones_idxes),
                        rssm_zero_recurrent[: len(dones_idxes)],
                        rssm_zero_stochastic[: len(dones_idxes)],
                        valid=False,
                    )
                )
            rb.add(reset_data, dones_idxes, validate_args=cfg.buffer.validate_args)

            step_data["rewards"][:, dones_idxes] = 0
            step_data["terminated"][:, dones_idxes] = 0
            step_data["truncated"][:, dones_idxes] = 0
            step_data["is_first"][:, dones_idxes] = 1
            reset_mask = np.zeros((num_envs, 1), np.float32)
            reset_mask[dones_idxes] = 1.0
            player.init_states(params["world_model"], reset_mask)

        # ---- log (reference dreamer_v3.py:747-793) ------------------------
        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            # the sentinel sees the raw per-gradient-step rows before the
            # aggregator's NaN filtering drops them (warn/halt policies; the
            # skip_update selection already happened in-graph)
            metrics_drain.flush_into(
                aggregator,
                metric_order,
                observer=lambda rows: diag.observe_rows(policy_step_count, metric_order, rows),
                extra_observer=lambda extras: diag.on_health(
                    policy_step_count, mean_stats(extras)
                ),
            )
            metrics_dict = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/train_time", 0) > 0:
                metrics_dict["Time/sps_train"] = (train_step_count - last_train) / timers["Time/train_time"]
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics_dict["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) * cfg.env.action_repeat
                ) / timers["Time/env_interaction_time"]
            if policy_step_count > 0:
                metrics_dict["Params/replay_ratio"] = cumulative_grad_steps / policy_step_count
            if runtime.is_global_zero:
                logger.log_metrics(metrics_dict, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count
            last_train = train_step_count

        # ---- checkpoint (reference dreamer_v3.py:795-826) -----------------
        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                **{k: jax.tree_util.tree_map(np.asarray, v) for k, v in params.items()},
                "opt_states": jax.tree_util.tree_map(np.asarray, opt_states),
                "moments": jax.tree_util.tree_map(np.asarray, moments_state),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "batch_size": cfg.algo.per_rank_batch_size * world_size,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            with diag.span("checkpoint"):
                runtime.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=rb if cfg.buffer.checkpoint else None,
                )
            diag.on_checkpoint(policy_step_count, ckpt_path)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    cumulative_rew = None
    if runtime.is_global_zero and cfg.algo.run_test:
        if final_test_fn is None:
            cumulative_rew = test(
                player, params["world_model"], player_actor_fn(params, True), runtime, cfg, log_dir, greedy=False
            )
        else:
            cumulative_rew = final_test_fn(player, params, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    if cfg.model_manager.disabled is False and runtime.is_global_zero:  # pragma: no cover
        from sheeprl_tpu.utils.mlflow import log_models

        log_models(cfg, params, log_dir)
    logger.finalize()
    diag.close("completed")
    return cumulative_rew
