"""DreamerV3 helpers (reference /root/reference/sheeprl/algos/dreamer_v3/utils.py).

``Moments`` is a pure-functional EMA of return percentiles: carried as a tiny
state pytree updated inside the jitted train step.  The reference gathers
values across ranks via ``fabric.all_gather`` before the quantile
(utils.py:56-64); inside the shard_map'd train step the same semantics is an
explicit ``lax.all_gather`` over the data axis before ``jnp.quantile``
(``axis_name`` below), so every device EMAs the *global* percentiles.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: Replay keys carrying the player's post-step RSSM state when
#: ``algo.rssm_chunks > 1`` (SEED-RL/R2D2-style stored-state chunking):
#: ``rssm_recurrent``/``rssm_posterior`` are the state AFTER observing the
#: row's obs, ``rssm_valid`` is 1.0 only on rows the player actually wrote
#: (prefill and episode-end bookkeeping rows carry zeros + valid=0, and a
#: chunk starting there falls back to the learned initial state — exactly
#: what the unchunked scan does at every sampled-sequence start).
RSSM_STATE_KEYS = ("rssm_recurrent", "rssm_posterior", "rssm_valid")


def rssm_scan_spec(cfg) -> Tuple[int, int]:
    """``(chunks, burn_in)`` from ``algo.rssm_chunks`` /
    ``algo.rssm_chunk_burn_in`` — shared by the DV3/JEPA/P2E train-step
    builders so the three can never drift.  Configs without the keys (the
    DV1/DV2 family) resolve to ``(1, 0)`` = today's sequential scan."""
    chunks = int(cfg.algo.get("rssm_chunks", 1) or 1)
    burn_in = int(cfg.algo.get("rssm_chunk_burn_in", 0) or 0)
    if chunks < 1:
        raise ValueError(f"algo.rssm_chunks must be >= 1, got {chunks}")
    if burn_in < 0:
        raise ValueError(f"algo.rssm_chunk_burn_in must be >= 0, got {burn_in}")
    return chunks, burn_in


def chunked_dynamic_scan(
    scan_body,
    batch_actions: jax.Array,
    embedded: jax.Array,
    is_first: jax.Array,
    key: jax.Array,
    *,
    stoch_flat: int,
    recurrent_size: int,
    cdt,
    chunks: int = 1,
    burn_in: int = 0,
    stored_recurrent: jax.Array | None = None,
    stored_posterior: jax.Array | None = None,
    stored_valid: jax.Array | None = None,
    unroll: int = 1,
):
    """Run the T-step dynamic-learning scan, optionally split into ``chunks``
    independent chunks whose initial states come from replay-stored RSSM
    states — the chunk axis is folded into the batch axis, so the GRU GEMM
    runs at ``B * chunks`` rows instead of ``B`` (PERF.md §4: MFU rises
    exactly as the effective row count widens; the trade is strict recurrence
    across chunk boundaries for stored — possibly stale — states, the
    SEED-RL/R2D2 playbook).

    ``scan_body`` is the per-step body the callers already wrote:
    ``((posterior, recurrent), (action_t, embed_t, is_first_t, key_t)) ->
    ((posterior, recurrent), ys)``.  Returns the stacked ``ys`` pytree in the
    original ``[T, B, ...]`` layout.

    * ``chunks == 1`` reproduces today's sequential scan **bit-identically**
      (same zero init, same ``jax.random.split(key, T)`` per-step keys, same
      op order — golden-tested in ``tests/test_algos/test_rssm_chunks.py``).
    * ``chunks > 1``: row ``t`` of chunk ``k`` starts at ``t0 = k*T/K``; its
      initial carry is the stored state at row ``t0 - 1`` (chunk 0 keeps the
      zero init + forced ``is_first``).  A stored state marked invalid
      (``rssm_valid == 0``) turns the chunk start into a fresh-sequence start
      via the ``is_first`` reset path.
    * ``burn_in > 0``: before the gradient region, rows ``[t0 - burn_in, t0)``
      are re-run from the state stored at ``t0 - burn_in - 1`` and the
      resulting carry — gradients stopped — re-freshens each chunk's initial
      state (R2D2's burn-in, folded over chunks the same way).
    """
    T, B = batch_actions.shape[:2]
    if chunks <= 1:
        keys_t = jax.random.split(key, T)
        init = (jnp.zeros((B, stoch_flat), cdt), jnp.zeros((B, recurrent_size), cdt))
        _, ys = jax.lax.scan(
            scan_body, init, (batch_actions, embedded, is_first, keys_t), unroll=unroll
        )
        return ys

    K = int(chunks)
    if T % K != 0:
        raise ValueError(f"algo.rssm_chunks ({K}) must divide the sequence length ({T})")
    C = T // K
    if not 0 <= burn_in < C:
        raise ValueError(
            f"algo.rssm_chunk_burn_in ({burn_in}) must be in [0, chunk_length) = [0, {C})"
        )
    if stored_recurrent is None or stored_posterior is None:
        raise ValueError(
            "algo.rssm_chunks > 1 needs the replay-stored RSSM state keys "
            f"{RSSM_STATE_KEYS[:2]} in the batch (enabled automatically by the "
            "training loop when the knob is set — old replay checkpoints "
            "collected without it cannot be chunk-trained)"
        )

    def fold(x):  # [T, B, ...] -> [C, K*B, ...] (row t = k*C + c -> (c, k*B+b))
        x = x.reshape((K, C) + x.shape[1:])
        x = jnp.moveaxis(x, 0, 1)
        return x.reshape((C, K * B) + x.shape[3:])

    def unfold(y):  # inverse of fold on the stacked outputs
        y = y.reshape((C, K, B) + y.shape[2:])
        y = jnp.moveaxis(y, 1, 0)
        return y.reshape((T, B) + y.shape[3:])

    stored_z = jax.lax.stop_gradient(stored_posterior).astype(cdt)
    stored_h = jax.lax.stop_gradient(stored_recurrent).astype(cdt)
    valid = (
        jax.lax.stop_gradient(stored_valid).astype(cdt)
        if stored_valid is not None
        else jnp.ones((T, B, 1), cdt)
    )
    k_main, k_burn = jax.random.split(key)
    boundary_rows = np.arange(1, K) * C  # first row of chunks 1..K-1 (static)

    if burn_in > 0:
        # burn-in: re-run the `burn_in` rows before each boundary from the
        # state stored just before them; only the final carry is used, and it
        # is gradient-stopped, so no gradient flows through the burn scan
        burn_rows = boundary_rows[:, None] - burn_in + np.arange(burn_in)[None, :]

        def gather_fold(x):  # rows [K-1, burn_in] of [T, B, ...] -> [burn_in, (K-1)*B, ...]
            g = x[burn_rows]
            g = jnp.moveaxis(g, 0, 1)
            return g.reshape((burn_in, (K - 1) * B) + g.shape[3:])

        init_rows = boundary_rows - burn_in - 1
        z0 = stored_z[init_rows].reshape(((K - 1) * B, stoch_flat))
        h0 = stored_h[init_rows].reshape(((K - 1) * B, recurrent_size))
        bf = gather_fold(is_first)
        invalid = 1.0 - valid[init_rows].reshape(((K - 1) * B, 1))
        bf = bf.at[0].set(jnp.maximum(bf[0], invalid))
        xs_burn = (
            gather_fold(batch_actions),
            gather_fold(embedded),
            bf,
            jax.random.split(k_burn, burn_in),
        )
        (z_fresh, h_fresh), _ = jax.lax.scan(scan_body, (z0, h0), xs_burn, unroll=unroll)
        z_rest = jax.lax.stop_gradient(z_fresh).reshape((K - 1, B, stoch_flat))
        h_rest = jax.lax.stop_gradient(h_fresh).reshape((K - 1, B, recurrent_size))
        is_first_adj = is_first
    else:
        init_rows = boundary_rows - 1
        z_rest = stored_z[init_rows]
        h_rest = stored_h[init_rows]
        # a chunk starting on a row whose predecessor was never written by
        # the player (prefill / bookkeeping) resets like a sequence start
        invalid = 1.0 - valid[init_rows]  # [K-1, B, 1]
        is_first_adj = is_first.at[boundary_rows].set(
            jnp.maximum(is_first[boundary_rows], invalid)
        )

    z_init = jnp.concatenate([jnp.zeros((1, B, stoch_flat), cdt), z_rest], axis=0)
    h_init = jnp.concatenate([jnp.zeros((1, B, recurrent_size), cdt), h_rest], axis=0)
    init = (z_init.reshape((K * B, stoch_flat)), h_init.reshape((K * B, recurrent_size)))
    xs = (fold(batch_actions), fold(embedded), fold(is_first_adj), jax.random.split(k_main, C))
    _, ys = jax.lax.scan(scan_body, init, xs, unroll=unroll)
    return jax.tree_util.tree_map(unfold, ys)


AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}


def init_moments_state() -> Dict[str, jax.Array]:
    return {"low": jnp.zeros(()), "high": jnp.zeros(())}


def update_moments(
    state: Dict[str, jax.Array],
    x: jax.Array,
    decay: float = 0.99,
    max_: float = 1.0,
    percentile_low: float = 0.05,
    percentile_high: float = 0.95,
    axis_name: str | None = None,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Return (offset, invscale, new_state) (reference Moments.forward,
    utils.py:56-64).  With ``axis_name`` set (inside shard_map) the quantile
    is computed over the all-gathered values from every device."""
    from sheeprl_tpu.parallel.dp import all_gather_cat

    x = all_gather_cat(jax.lax.stop_gradient(x).astype(jnp.float32), axis_name)
    low = jnp.quantile(x, percentile_low)
    high = jnp.quantile(x, percentile_high)
    new_low = decay * state["low"] + (1 - decay) * low
    new_high = decay * state["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / max_, new_high - new_low)
    return new_low, invscale, {"low": new_low, "high": new_high}


def prepare_obs(
    obs: Dict[str, np.ndarray],
    *,
    cnn_keys: Sequence[str] = (),
    mlp_keys: Sequence[str] = (),
    num_envs: int = 1,
    sharding: Any = None,
) -> Dict[str, jax.Array]:
    """Host obs → device arrays ``[num_envs, ...]``; pixels scaled to
    [-0.5, 0.5] (reference utils.py:80-92).  The whole slab is staged in ONE
    ``jax.device_put`` (pass a reused ``sharding`` from the hot loops —
    ``envs/player.py::obs_sharding``); pixels transfer uint8 and are cast +
    scaled on device (4x less host→HBM traffic, identical float32 values —
    same policy as the ppo path)."""
    host: Dict[str, np.ndarray] = {}
    for k in cnn_keys:
        v = np.asarray(obs[k])
        host[k] = v.reshape(num_envs, -1, *v.shape[-2:])
    for k in mlp_keys:
        host[k] = np.asarray(obs[k], np.float32).reshape(num_envs, -1)
    dev = jax.device_put(host, sharding) if sharding is not None else jax.device_put(host)
    cnn = set(cnn_keys)
    return {k: (v.astype(jnp.float32) / 255.0 - 0.5 if k in cnn else v) for k, v in dev.items()}


def test(player, wm_params, actor_params, runtime, cfg, log_dir: str, test_name: str = "", greedy: bool = True):
    """One test episode (reference utils.py:95-140)."""
    from sheeprl_tpu.envs.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    saved_num_envs = player.num_envs
    player.num_envs = 1
    player.state = None
    player.init_states(wm_params)
    key = jax.random.PRNGKey(cfg.seed or 0)
    step = 0
    while not done:
        key, sub = jax.random.split(key)
        torch_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, mlp_keys=cfg.algo.mlp_keys.encoder)
        mask = {k: v for k, v in torch_obs.items() if k.startswith("mask")} or None
        actions = np.asarray(
            player.get_actions(wm_params, actor_params, torch_obs, sub, greedy=greedy, mask=mask)
        )
        if player.actor_def.is_continuous:
            real_actions = actions.reshape(env.action_space.shape)
        else:
            # one-hot concat -> per-head argmax indices
            idxs = []
            start = 0
            for d in player.actions_dim:
                idxs.append(np.argmax(actions[..., start : start + d], axis=-1))
                start += d
            real_actions = np.stack(idxs, axis=-1).reshape(env.action_space.shape)
        obs, reward, terminated, truncated, _ = env.step(real_actions)
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(reward)
        step += 1
    env.close()
    player.num_envs = saved_num_envs
    player.state = None
    return cumulative_rew
