"""DreamerV3 helpers (reference /root/reference/sheeprl/algos/dreamer_v3/utils.py).

``Moments`` is a pure-functional EMA of return percentiles: carried as a tiny
state pytree updated inside the jitted train step.  The reference gathers
values across ranks via ``fabric.all_gather`` before the quantile
(utils.py:56-64); inside the shard_map'd train step the same semantics is an
explicit ``lax.all_gather`` over the data axis before ``jnp.quantile``
(``axis_name`` below), so every device EMAs the *global* percentiles.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}


def init_moments_state() -> Dict[str, jax.Array]:
    return {"low": jnp.zeros(()), "high": jnp.zeros(())}


def update_moments(
    state: Dict[str, jax.Array],
    x: jax.Array,
    decay: float = 0.99,
    max_: float = 1.0,
    percentile_low: float = 0.05,
    percentile_high: float = 0.95,
    axis_name: str | None = None,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """Return (offset, invscale, new_state) (reference Moments.forward,
    utils.py:56-64).  With ``axis_name`` set (inside shard_map) the quantile
    is computed over the all-gathered values from every device."""
    from sheeprl_tpu.parallel.dp import all_gather_cat

    x = all_gather_cat(jax.lax.stop_gradient(x).astype(jnp.float32), axis_name)
    low = jnp.quantile(x, percentile_low)
    high = jnp.quantile(x, percentile_high)
    new_low = decay * state["low"] + (1 - decay) * low
    new_high = decay * state["high"] + (1 - decay) * high
    invscale = jnp.maximum(1.0 / max_, new_high - new_low)
    return new_low, invscale, {"low": new_low, "high": new_high}


def prepare_obs(
    obs: Dict[str, np.ndarray],
    *,
    cnn_keys: Sequence[str] = (),
    mlp_keys: Sequence[str] = (),
    num_envs: int = 1,
    sharding: Any = None,
) -> Dict[str, jax.Array]:
    """Host obs → device arrays ``[num_envs, ...]``; pixels scaled to
    [-0.5, 0.5] (reference utils.py:80-92).  The whole slab is staged in ONE
    ``jax.device_put`` (pass a reused ``sharding`` from the hot loops —
    ``envs/player.py::obs_sharding``); pixels transfer uint8 and are cast +
    scaled on device (4x less host→HBM traffic, identical float32 values —
    same policy as the ppo path)."""
    host: Dict[str, np.ndarray] = {}
    for k in cnn_keys:
        v = np.asarray(obs[k])
        host[k] = v.reshape(num_envs, -1, *v.shape[-2:])
    for k in mlp_keys:
        host[k] = np.asarray(obs[k], np.float32).reshape(num_envs, -1)
    dev = jax.device_put(host, sharding) if sharding is not None else jax.device_put(host)
    cnn = set(cnn_keys)
    return {k: (v.astype(jnp.float32) / 255.0 - 0.5 if k in cnn else v) for k, v in dev.items()}


def test(player, wm_params, actor_params, runtime, cfg, log_dir: str, test_name: str = "", greedy: bool = True):
    """One test episode (reference utils.py:95-140)."""
    from sheeprl_tpu.envs.env import make_env

    env = make_env(cfg, cfg.seed, 0, log_dir, "test" + (f"_{test_name}" if test_name else ""))()
    done = False
    cumulative_rew = 0.0
    obs = env.reset(seed=cfg.seed)[0]
    saved_num_envs = player.num_envs
    player.num_envs = 1
    player.state = None
    player.init_states(wm_params)
    key = jax.random.PRNGKey(cfg.seed or 0)
    step = 0
    while not done:
        key, sub = jax.random.split(key)
        torch_obs = prepare_obs(obs, cnn_keys=cfg.algo.cnn_keys.encoder, mlp_keys=cfg.algo.mlp_keys.encoder)
        mask = {k: v for k, v in torch_obs.items() if k.startswith("mask")} or None
        actions = np.asarray(
            player.get_actions(wm_params, actor_params, torch_obs, sub, greedy=greedy, mask=mask)
        )
        if player.actor_def.is_continuous:
            real_actions = actions.reshape(env.action_space.shape)
        else:
            # one-hot concat -> per-head argmax indices
            idxs = []
            start = 0
            for d in player.actions_dim:
                idxs.append(np.argmax(actions[..., start : start + d], axis=-1))
                start += d
            real_actions = np.stack(idxs, axis=-1).reshape(env.action_space.shape)
        obs, reward, terminated, truncated, _ = env.step(real_actions)
        done = bool(terminated or truncated or cfg.dry_run)
        cumulative_rew += float(reward)
        step += 1
    env.close()
    player.num_envs = saved_num_envs
    player.state = None
    return cumulative_rew
