"""DroQ helper surface (reference /root/reference/sheeprl/algos/droq/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.sac.utils import prepare_obs, test  # noqa: F401

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/alpha_loss",
}
MODELS_TO_REGISTER = {"agent"}
