"""DroQ agent (reference /root/reference/sheeprl/algos/droq/agent.py:20-276).

DroQ = SAC with Dropout+LayerNorm critics (https://arxiv.org/abs/2110.02034)
and a high replay ratio.  The critic ensemble is one vmapped module (N small
MLPs → one batched MXU matmul per layer); dropout uses flax's rng collection.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Tuple

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from sheeprl_tpu.algos.sac.agent import SACActor


class _DroQQNetwork(nn.Module):
    hidden_size: int = 256
    dropout: float = 0.01

    @nn.compact
    def __call__(self, obs: jax.Array, actions: jax.Array, deterministic: bool = False) -> jax.Array:
        x = jnp.concatenate([obs, actions], axis=-1)
        for _ in range(2):
            x = nn.Dense(self.hidden_size)(x)
            if self.dropout > 0:
                x = nn.Dropout(rate=self.dropout, deterministic=deterministic)(x)
            x = nn.LayerNorm()(x)
            x = jax.nn.relu(x)
        return nn.Dense(1)(x)


class DroQCritics(nn.Module):
    """Vmapped ensemble of DroQ Q-networks, output ``[..., N]``."""

    num_critics: int = 2
    hidden_size: int = 256
    dropout: float = 0.01

    @nn.compact
    def __call__(self, obs: jax.Array, actions: jax.Array, deterministic: bool = False) -> jax.Array:
        vmapped = nn.vmap(
            _DroQQNetwork,
            in_axes=(None, None, None),
            out_axes=-1,
            axis_size=self.num_critics,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
        )(hidden_size=self.hidden_size, dropout=self.dropout)
        return vmapped(obs, actions, deterministic)[..., 0, :]


def build_agent(
    runtime,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    action_space: gymnasium.spaces.Box,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """Returns ``(actor_def, critic_def, params, target_entropy)``
    (reference agent.py:212-276)."""
    act_dim = int(prod(action_space.shape))
    obs_dim = int(sum(prod(obs_space[k].shape) for k in cfg.algo.mlp_keys.encoder))
    actor_def = SACActor(
        action_dim=act_dim,
        hidden_size=cfg.algo.actor.hidden_size,
        action_low=tuple(np.asarray(action_space.low, dtype=np.float32).reshape(-1).tolist()),
        action_high=tuple(np.asarray(action_space.high, dtype=np.float32).reshape(-1).tolist()),
    )
    critic_def = DroQCritics(
        num_critics=cfg.algo.critic.n,
        hidden_size=cfg.algo.critic.hidden_size,
        dropout=cfg.algo.critic.dropout,
    )
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(int(cfg.seed or 0)), 3)
    dummy_obs = jnp.zeros((1, obs_dim), jnp.float32)
    dummy_act = jnp.zeros((1, act_dim), jnp.float32)
    actor_params = actor_def.init(k1, dummy_obs)
    critic_params = critic_def.init({"params": k2, "dropout": k3}, dummy_obs, dummy_act)
    params = {
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
        "log_alpha": jnp.log(jnp.asarray([cfg.algo.alpha.alpha], jnp.float32)),
    }
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    target_entropy = -act_dim
    return actor_def, critic_def, params, target_entropy
