"""DroQ training loop — TPU-native re-design of
/root/reference/sheeprl/algos/droq/droq.py:30-436.

Differences from SAC (reference droq.py:60-140):
- dropout critics with per-gradient-step EMA of each target network;
- the actor/alpha update uses a separate minibatch and averages (not mins)
  the ensemble Q-values;
- high replay ratio (20 gradient steps per policy step by default).

The reference updates each of the N critics sequentially against the same
soft target; with one shared optimizer this equals a joint update on the
summed per-critic MSE, so here all critics update in one vmapped step.
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.droq.agent import build_agent
from sheeprl_tpu.algos.droq.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
from sheeprl_tpu.algos.sac.loss import conservative_q_penalty, entropy_loss, policy_loss
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.parallel.dp import P, batch_spec, dp_axis, dp_jit, fold_key, pmean_tree, stage, local_sample_size
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import Ratio, get_diagnostics, save_configs


def make_train_step(actor_def, critic_def, optimizers, cfg, target_entropy: float, mesh=None):
    axis = dp_axis(mesh)
    cdt = compute_dtype_of(cfg)
    tau = cfg.algo.tau
    gamma = cfg.algo.gamma
    # conservative Q penalty (offline mode, howto/offline_rl.md): trace-time
    # constant — the cql_alpha=0 graph is bit-identical to the online step
    offline_cfg = cfg.algo.get("offline") or {}
    cql_alpha = float(offline_cfg.get("cql_alpha", 0.0) or 0.0)
    cql_samples = int(offline_cfg.get("cql_samples", 4) or 4)
    act_low = np.asarray(actor_def.action_low, np.float32).reshape(-1)
    act_high = np.asarray(actor_def.action_high, np.float32).reshape(-1)
    if cql_alpha > 0 and not (np.isfinite(act_low).all() and np.isfinite(act_high).all()):
        raise ValueError(
            "algo.offline.cql_alpha > 0 needs finite action bounds for its uniform "
            "action proposals (set algo.offline.action_low/high)"
        )

    def one_step(carry, inp):
        params, opt_states = carry
        batch, actor_batch, key = inp
        key = fold_key(key, axis)
        if cql_alpha > 0:
            key, k_cql = jax.random.split(key)
        k_next, k_drop, k_actor, k_drop2 = jax.random.split(key, 4)
        obs_c = cast_floating(batch["observations"], cdt)
        next_obs_c = cast_floating(batch["next_observations"], cdt)
        actor_obs_c = cast_floating(actor_batch["observations"], cdt)

        # --- critic update (reference droq.py:95-120) ---------------------
        next_actions, next_logprobs = actor_def.apply(
            cast_floating(params["actor"], cdt), next_obs_c, k_next, method="sample_and_log_prob"
        )
        next_q = critic_def.apply(
            cast_floating(params["target_critic"], cdt), next_obs_c, next_actions, True
        ).astype(jnp.float32)
        min_next_q = jnp.min(next_q, axis=-1, keepdims=True)
        alpha = jnp.exp(params["log_alpha"])
        next_qf_value = batch["rewards"] + (1 - batch["terminated"]) * gamma * (
            min_next_q - alpha * next_logprobs.astype(jnp.float32)
        )
        next_qf_value = jax.lax.stop_gradient(next_qf_value)

        def qf_loss_fn(critic_params):
            qf_values = critic_def.apply(
                cast_floating(critic_params, cdt),
                obs_c,
                cast_floating(batch["actions"], cdt),
                False,
                rngs={"dropout": k_drop},
            ).astype(jnp.float32)
            loss = jnp.sum(jnp.mean((qf_values - next_qf_value) ** 2, axis=tuple(range(qf_values.ndim - 1))))
            if cql_alpha > 0:
                # proposals take the deterministic critic pass (no dropout
                # rng needed for the penalty term)
                actor_c = cast_floating(params["actor"], cdt)
                critic_c = cast_floating(critic_params, cdt)
                loss = loss + cql_alpha * conservative_q_penalty(
                    k_cql,
                    obs_c,
                    qf_values,
                    lambda o, k: actor_def.apply(actor_c, o, k, method="sample_and_log_prob"),
                    lambda o, a: critic_def.apply(critic_c, o, a, True),
                    act_low,
                    act_high,
                    cql_samples,
                )
            return loss

        qf_l, qf_grads = jax.value_and_grad(qf_loss_fn)(params["critic"])
        qf_grads = pmean_tree(qf_grads, axis)
        updates, opt_states["critic"] = optimizers["critic"].update(
            qf_grads, opt_states["critic"], params["critic"]
        )
        params["critic"] = optax.apply_updates(params["critic"], updates)
        params["target_critic"] = optax.incremental_update(params["critic"], params["target_critic"], tau)

        # --- actor update on its own batch (reference droq.py:122-131) ----
        def actor_loss_fn(actor_params):
            actions, logprobs = actor_def.apply(
                cast_floating(actor_params, cdt), actor_obs_c, k_actor, method="sample_and_log_prob"
            )
            q = critic_def.apply(
                cast_floating(params["critic"], cdt), actor_obs_c, actions, False, rngs={"dropout": k_drop2}
            ).astype(jnp.float32)
            mean_q = jnp.mean(q, axis=-1, keepdims=True)
            alpha = jnp.exp(params["log_alpha"])
            return policy_loss(alpha, logprobs.astype(jnp.float32), mean_q), logprobs

        (actor_l, logprobs), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(params["actor"])
        actor_grads = pmean_tree(actor_grads, axis)
        updates, opt_states["actor"] = optimizers["actor"].update(
            actor_grads, opt_states["actor"], params["actor"]
        )
        params["actor"] = optax.apply_updates(params["actor"], updates)

        # --- alpha update (reference droq.py:133-139) ---------------------
        def alpha_loss_fn(log_alpha):
            return entropy_loss(log_alpha, logprobs, target_entropy)

        alpha_l, alpha_grads = jax.value_and_grad(alpha_loss_fn)(params["log_alpha"])
        alpha_grads = pmean_tree(alpha_grads, axis)
        updates, opt_states["alpha"] = optimizers["alpha"].update(
            alpha_grads, opt_states["alpha"], params["log_alpha"]
        )
        params["log_alpha"] = optax.apply_updates(params["log_alpha"], updates)

        return (params, opt_states), jnp.stack([qf_l, actor_l, alpha_l])

    def update(params, opt_states, data, actor_data, keys):
        (params, opt_states), losses = jax.lax.scan(one_step, (params, opt_states), (data, actor_data, keys))
        return params, opt_states, pmean_tree(jnp.mean(losses, axis=0), axis)

    return dp_jit(
        update,
        mesh,
        in_specs=(P(), P(), batch_spec(batch_axis=1), batch_spec(batch_axis=1), P()),
        out_specs=(P(), P(), P()),
        donate_argnums=(0, 1),
    )


@register_algorithm()
def main(runtime, cfg):
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs

    rng_key = runtime.seed_everything(cfg.seed)
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    if not isinstance(action_space, gym.spaces.Box):
        raise ValueError("DroQ supports only continuous (Box) action spaces")
    mlp_keys = cfg.algo.mlp_keys.encoder

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    actor_def, critic_def, params, target_entropy = build_agent(
        runtime, cfg, observation_space, action_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)
    optimizers = {
        "actor": instantiate(cfg.algo.actor.optimizer),
        "critic": instantiate(cfg.algo.critic.optimizer),
        "alpha": instantiate(cfg.algo.alpha.optimizer),
    }
    opt_states = {
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
        "alpha": optimizers["alpha"].init(params["log_alpha"]),
    }
    if state and "opt_states" in state:
        opt_states = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_states,
            state["opt_states"],
        )

    # telemetry + memory instrumentation — see tools/check_instrumentation.py
    train_step = diag.instrument(
        "train_step",
        make_train_step(
            actor_def, critic_def, optimizers, cfg, target_entropy, mesh=runtime.mesh if world_size > 1 else None
        ),
        kind="train",
        donate_argnums=(0, 1),
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_states)

    @jax.jit
    def policy_step(actor_params, obs, key):
        actions, _ = actor_def.apply(actor_params, obs, key, method="sample_and_log_prob")
        return actions

    rb = ReplayBuffer(
        cfg.buffer.size,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=("observations",),
    )
    if state and "rb" in state and state["rb"] is not None:
        rb.load_state_dict(state["rb"])

    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    policy_steps_per_iter = int(num_envs)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    learning_starts = cfg.algo.learning_starts // policy_steps_per_iter if not cfg.dry_run else 0
    prefill_steps = learning_starts - int(learning_starts > 0)
    if cfg.checkpoint.resume_from:
        learning_starts += start_iter
        prefill_steps += start_iter

    ratio = Ratio(cfg.algo.replay_ratio, pretrain_steps=cfg.algo.per_rank_pretrain_steps)
    if state and "ratio" in state:
        ratio.load_state_dict(state["ratio"])

    batch_size = cfg.algo.per_rank_batch_size
    obs, _ = envs.reset(seed=cfg.seed)

    for iter_num in range(start_iter, total_iters + 1):
        policy_step_count += policy_steps_per_iter
        with timer("Time/env_interaction_time"):
            if iter_num <= learning_starts:
                actions = envs.action_space.sample()
            else:
                rng_key, step_key = jax.random.split(rng_key)
                flat_obs = prepare_obs(obs, mlp_keys=mlp_keys, num_envs=num_envs)
                actions = np.asarray(policy_step(params["actor"], flat_obs, step_key))
            next_obs, rewards, terminated, truncated, info = envs.step(
                actions.reshape(envs.action_space.shape)
            )
            rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, -1)

        if "final_info" in info and "episode" in info["final_info"]:
            ep = info["final_info"]["episode"]
            mask = ep.get("_r", info["final_info"].get("_episode"))
            if mask is not None and np.any(mask):
                for r, l in zip(ep["r"][mask], ep["l"][mask]):
                    aggregator.update("Rewards/rew_avg", float(r))
                    aggregator.update("Game/ep_len_avg", float(l))

        real_next_obs = {k: np.asarray(next_obs[k]).copy() for k in mlp_keys}
        if "final_obs" in info:
            for idx, final_obs in enumerate(info["final_obs"]):
                if final_obs is not None:
                    for k in mlp_keys:
                        real_next_obs[k][idx] = np.asarray(final_obs[k])

        step_data: Dict[str, np.ndarray] = step_slab(
            num_envs,
            {
                "observations": np.concatenate(
                    [np.asarray(obs[k], np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
                ),
                "next_observations": np.concatenate(
                    [real_next_obs[k].astype(np.float32).reshape(num_envs, -1) for k in mlp_keys], axis=-1
                ),
                "actions": actions.reshape(num_envs, -1),
                "rewards": rewards,
                "terminated": terminated,
                "truncated": truncated,
            },
            dtypes={"terminated": np.float32, "truncated": np.float32},
        )
        rb.add(step_data, validate_args=cfg.buffer.validate_args)
        obs = next_obs

        if iter_num >= learning_starts:
            per_rank_gradient_steps = ratio(policy_step_count - prefill_steps * policy_steps_per_iter)
            if cfg.dry_run:
                per_rank_gradient_steps = 1
            if per_rank_gradient_steps > 0:
                with timer("Time/train_time"):
                    G = per_rank_gradient_steps
                    sample = rb.sample(batch_size=local_sample_size(batch_size * world_size), n_samples=G)
                    actor_sample = rb.sample(batch_size=local_sample_size(batch_size * world_size), n_samples=G)
                    dp_mesh = runtime.mesh if world_size > 1 else None
                    data = stage(
                        {
                            k: np.asarray(v, np.float32)
                            for k, v in sample.items()
                            if k in ("observations", "next_observations", "actions", "rewards", "terminated")
                        },
                        dp_mesh,
                        batch_axis=1,
                    )
                    actor_data = stage(
                        {"observations": np.asarray(actor_sample["observations"], np.float32)}, dp_mesh, batch_axis=1
                    )
                    rng_key, scan_key = jax.random.split(rng_key)
                    keys = jax.random.split(scan_key, G)
                    params, opt_states, losses = train_step(params, opt_states, data, actor_data, keys)
                    losses = np.asarray(losses)
                aggregator.update("Loss/value_loss", float(losses[0]))
                aggregator.update("Loss/policy_loss", float(losses[1]))
                aggregator.update("Loss/alpha_loss", float(losses[2]))

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "agent": jax.tree_util.tree_map(np.asarray, params),
                "opt_states": jax.tree_util.tree_map(np.asarray, opt_states),
                "ratio": ratio.state_dict(),
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": batch_size * world_size,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            runtime.call(
                "on_checkpoint_coupled",
                ckpt_path=ckpt_path,
                state=ckpt_state,
                replay_buffer=rb if cfg.buffer.checkpoint else None,
            )
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(actor_def.apply, params["actor"], test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    logger.finalize()
