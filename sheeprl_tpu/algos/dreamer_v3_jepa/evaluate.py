"""DreamerV3-JEPA evaluation entrypoint
(reference /root/reference/sheeprl/algos/dreamer_v3_jepa/evaluate.py): identical
shape to the DV3 evaluator — the JEPA heads only matter at train time, the
player needs the world model + task actor."""

from __future__ import annotations

from typing import Any, Dict

import gymnasium as gym

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3
from sheeprl_tpu.algos.dreamer_v3.utils import test
from sheeprl_tpu.algos.dreamer_v3_jepa.agent import build_agent
from sheeprl_tpu.envs.env import make_env
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.registry import register_evaluation


@register_evaluation(algorithms="dreamer_v3_jepa")
def evaluate_dreamer_v3_jepa(runtime, cfg, state: Dict[str, Any]) -> None:
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    env = make_env(cfg, cfg.seed, 0, log_dir, "test")()
    action_space = env.action_space
    observation_space = env.observation_space
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )
    world_model_def, actor_def, critic_def, _jepa_heads, params = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["world_model"],
        state["actor"],
        state["critic"],
        state.get("target_critic"),
    )
    player = PlayerDV3(world_model_def, actor_def, actions_dim, 1)
    env.close()
    cumulative_rew = test(player, params["world_model"], params["actor"], runtime, cfg, log_dir, greedy=False)
    logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, 0)
    logger.finalize()
