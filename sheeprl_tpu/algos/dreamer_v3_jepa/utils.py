"""DreamerV3-JEPA helper surface
(reference /root/reference/sheeprl/algos/dreamer_v3_jepa/utils.py)."""

from __future__ import annotations

from sheeprl_tpu.algos.dreamer_v3.utils import (  # noqa: F401
    init_moments_state,
    prepare_obs,
    test,
    update_moments,
)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "Loss/jepa_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic", "moments"}
