"""DreamerV3-JEPA agent (fork feature, reference
/root/reference/sheeprl/algos/dreamer_v3_jepa/agent.py): DV3 with optional
decoder-free world model plus a JEPA head over the encoder.

Params layout extends DV3's with ``params["jepa"] = {projector, predictor,
target_encoder, target_projector}`` where the target branches are EMA copies
of the online encoder/projector params (reference JEPAHead deep-copy,
models/jepa.py:74-124).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3, build_agent as _dv3_build_agent
from sheeprl_tpu.models.jepa import JEPAPredictor, JEPAProjector

PlayerDV3JEPA = PlayerDV3


def encoder_subtree(wm_params: Dict[str, Any]) -> Dict[str, Any]:
    """Extract the encoder submodule params (enough for apply(method='encode'))."""
    inner = wm_params["params"]
    sub = {k: v for k, v in inner.items() if k in ("cnn_encoder", "mlp_encoder")}
    return {"params": sub}


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
    target_critic_state: Optional[Dict[str, Any]] = None,
):
    world_model_def, actor_def, critic_def, params = _dv3_build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        world_model_state,
        actor_state,
        critic_state,
        target_critic_state,
    )
    projector_def = JEPAProjector(proj_dim=cfg.algo.jepa_proj_dim, hidden=cfg.algo.jepa_hidden)
    predictor_def = JEPAPredictor(proj_dim=cfg.algo.jepa_proj_dim, hidden=cfg.algo.jepa_hidden)

    # probe the encoder output dim with a dummy forward
    from math import prod

    sample_obs: Dict[str, jax.Array] = {}
    for k in cfg.algo.cnn_keys.encoder:
        sample_obs[k] = jnp.zeros((1, 1) + tuple(obs_space[k].shape), jnp.float32)
    for k in cfg.algo.mlp_keys.encoder:
        sample_obs[k] = jnp.zeros((1, 1, int(prod(obs_space[k].shape))), jnp.float32)
    embedded = world_model_def.apply(params["world_model"], sample_obs, method="encode")
    k1, k2 = jax.random.split(jax.random.PRNGKey((cfg.seed or 0) + 1))
    projector_params = projector_def.init(k1, embedded)
    predictor_params = predictor_def.init(k2, jnp.zeros((1, cfg.algo.jepa_proj_dim), jnp.float32))
    if "jepa" not in params:
        params["jepa"] = {
            "projector": projector_params,
            "predictor": predictor_params,
            "target_encoder": jax.tree_util.tree_map(jnp.copy, encoder_subtree(params["world_model"])),
            "target_projector": jax.tree_util.tree_map(jnp.copy, projector_params),
        }
    if world_model_state is not None and isinstance(world_model_state, dict) and "jepa" in world_model_state:
        params["jepa"] = jax.tree_util.tree_map(jnp.asarray, world_model_state["jepa"])
    return world_model_def, actor_def, critic_def, (projector_def, predictor_def), params
