"""DreamerV3-JEPA training loop (fork feature, reference
/root/reference/sheeprl/algos/dreamer_v3_jepa/dreamer_v3_jepa.py:100-909).

DV3 with a decoder-optional world model and a JEPA auxiliary loss on the
encoder: two masked views of the batch are encoded (online vs EMA-target
branch) and a cosine prediction loss (weight ``jepa_coef``) is added to the
world-model objective; the target encoder/projector track the online ones
with momentum ``jepa_ema`` (reference :230-246).  The JEPA projector and
predictor train under the world-model optimizer, exactly like the reference
attaches the head to the WorldModel module (agent.py:96).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import optax

from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import _dreamer_main
from sheeprl_tpu.algos.dreamer_v3.loss import reconstruction_loss
from sheeprl_tpu.algos.dreamer_v3.utils import chunked_dynamic_scan, rssm_scan_spec, update_moments
from sheeprl_tpu.algos.dreamer_v3_jepa.agent import build_agent as _build_agent_full, encoder_subtree
from sheeprl_tpu.algos.dreamer_v3_jepa.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER  # noqa: F401
from sheeprl_tpu.models.jepa import jepa_loss, make_two_views
from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    MSEDistribution,
    SymlogDistribution,
    TwoHotEncodingDistribution,
)
from sheeprl_tpu.ops.numerics import compute_lambda_values
from sheeprl_tpu.parallel.dp import P, batch_spec, dp_axis, dp_jit, fold_key, pmean_tree
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.registry import register_algorithm

_HEADS = {}  # filled by the wrapped build_agent; keyed per-process (single controller)


def _build_agent(runtime, actions_dim, is_continuous, cfg, obs_space, state):
    world_model_def, actor_def, critic_def, head_defs, params = _build_agent_full(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        obs_space,
        state["world_model"] if state else None,
        state["actor"] if state else None,
        state["critic"] if state else None,
        state["target_critic"] if state else None,
    )
    _HEADS["projector_def"], _HEADS["predictor_def"] = head_defs
    if state and "jepa" in state:
        import jax as _jax

        params["jepa"] = _jax.tree_util.tree_map(jnp.asarray, state["jepa"])
    return world_model_def, actor_def, critic_def, params


def make_train_step(
    world_model_def,
    actor_def,
    critic_def,
    optimizers,
    cfg,
    actions_dim: Sequence[int],
    is_continuous: bool,
    mesh=None,
):
    axis = dp_axis(mesh)
    cdt = compute_dtype_of(cfg)
    wm_cfg = cfg.algo.world_model
    stoch_flat = wm_cfg.stochastic_size * wm_cfg.discrete_size
    recurrent_size = wm_cfg.recurrent_model.recurrent_state_size
    horizon = cfg.algo.horizon
    gamma = cfg.algo.gamma
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_dec_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_dec_keys = list(cfg.algo.mlp_keys.decoder)
    jepa_coef = cfg.algo.jepa_coef
    ema_m = cfg.algo.jepa_ema
    # chunked sequence-parallel RSSM scan + unroll lever (inherited from the
    # shared DV3 config surface — see dreamer_v3.py::make_train_step)
    scan_unroll = int(cfg.algo.get("scan_unroll", 1))
    rssm_chunks, rssm_burn_in = rssm_scan_spec(cfg)
    projector_def = _HEADS["projector_def"]
    predictor_def = _HEADS["predictor_def"]

    from sheeprl_tpu.diagnostics.health import health_spec, health_stats
    from sheeprl_tpu.diagnostics.sentinel import select_finite, sentinel_spec

    sentinel = sentinel_spec(cfg)
    health = health_spec(cfg)

    def train_step(params, opt_states, moments_state, batch, key, tau):
        T, B = batch["actions"].shape[:2]
        key = fold_key(key, axis)
        k_wm, k_img, k_img_actions, k_views = jax.random.split(key, 4)

        # sentinel snapshots for the skip_update guard at the end.  tree_map
        # rebuilds every container (leaves shared): a plain dict(params) would
        # alias the nested params["jepa"] dict, which IS mutated in place
        # below, and the guard could never revert the JEPA heads
        if sentinel.skip_update:
            copy = lambda tree: jax.tree_util.tree_map(lambda leaf: leaf, tree)  # noqa: E731
            prev_state = (copy(params), copy(opt_states), moments_state)

        params["target_critic"] = jax.tree_util.tree_map(
            lambda c, t: tau * c + (1 - tau) * t, params["critic"], params["target_critic"]
        )

        target_obs = {k: batch[k] for k in set(cnn_keys + mlp_keys)}  # fp32 targets
        batch_obs = cast_floating(target_obs, cdt)
        # JEPA views need (T,B,C,H,W) pixels / (T,B,D) vectors
        view_obs = {k: batch_obs[k] for k in batch_obs}
        obs_q, obs_k = make_two_views(
            view_obs, k_views, cfg.algo.jepa_mask.erase_frac, cfg.algo.jepa_mask.vec_dropout
        )
        batch_actions = jnp.concatenate(
            [jnp.zeros_like(batch["actions"][:1]), batch["actions"][:-1]], axis=0
        ).astype(cdt)
        is_first = batch["is_first"].at[0].set(1.0).astype(cdt)

        def wm_loss_fn(combined):
            wm_params, jepa_online = combined
            wm_params = cast_floating(wm_params, cdt)
            jepa_online = cast_floating(jepa_online, cdt)
            embedded = world_model_def.apply(wm_params, batch_obs, method="encode")

            def scan_body(carry, x):
                posterior, recurrent = carry
                action_t, embed_t, is_first_t, key_t = x
                recurrent, posterior, _, post_logits, prior_logits = world_model_def.apply(
                    wm_params, posterior, recurrent, action_t, embed_t, is_first_t, key_t, method="dynamic"
                )
                return (posterior, recurrent), (recurrent, posterior, post_logits, prior_logits)

            recurrents, posteriors, post_logits, prior_logits = chunked_dynamic_scan(
                scan_body,
                batch_actions,
                embedded,
                is_first,
                k_wm,
                stoch_flat=stoch_flat,
                recurrent_size=recurrent_size,
                cdt=cdt,
                chunks=rssm_chunks,
                burn_in=rssm_burn_in,
                stored_recurrent=batch.get("rssm_recurrent"),
                stored_posterior=batch.get("rssm_posterior"),
                stored_valid=batch.get("rssm_valid"),
                unroll=scan_unroll,
            )
            latents = jnp.concatenate([posteriors, recurrents], axis=-1)
            recon = world_model_def.apply(wm_params, latents, method="decode")
            po = {k: MSEDistribution(recon[k], dims=len(recon[k].shape[2:])) for k in cnn_dec_keys}
            po.update({k: SymlogDistribution(recon[k], dims=len(recon[k].shape[2:])) for k in mlp_dec_keys})
            pr = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, latents, method="reward_logits"), dims=1
            )
            pc = Bernoulli(
                world_model_def.apply(wm_params, latents, method="continue_logits"), event_dims=1
            )
            continues_targets = 1 - batch["terminated"]
            pl = prior_logits.reshape(T, B, wm_cfg.stochastic_size, wm_cfg.discrete_size)
            ql = post_logits.reshape(T, B, wm_cfg.stochastic_size, wm_cfg.discrete_size)
            rec_loss, kl, state_loss, reward_loss, observation_loss, continue_loss = reconstruction_loss(
                po,
                {k: target_obs[k] for k in set(cnn_dec_keys + mlp_dec_keys)},
                pr,
                batch["rewards"],
                pl,
                ql,
                wm_cfg.kl_dynamic,
                wm_cfg.kl_representation,
                wm_cfg.kl_free_nats,
                wm_cfg.kl_regularizer,
                pc,
                continues_targets,
                wm_cfg.continue_scale_factor,
            )
            # --- JEPA auxiliary objective (reference :230-231) ------------
            jl = jepa_loss(
                lambda o: world_model_def.apply(wm_params, o, method="encode"),
                lambda o: world_model_def.apply(
                    cast_floating(params["jepa"]["target_encoder"], cdt), o, method="encode"
                ),
                projector_def,
                predictor_def,
                jepa_online["projector"],
                jepa_online["predictor"],
                cast_floating(params["jepa"]["target_projector"], cdt),
                obs_q,
                obs_k,
            )
            total = rec_loss + jepa_coef * jl
            aux = {
                "posteriors": posteriors,
                "recurrents": recurrents,
                "kl": kl,
                "state_loss": state_loss,
                "reward_loss": reward_loss,
                "observation_loss": observation_loss,
                "continue_loss": continue_loss,
                "jepa_loss": jl,
                "rec_loss": rec_loss,
            }
            return total, aux

        jepa_online = {"projector": params["jepa"]["projector"], "predictor": params["jepa"]["predictor"]}
        (total_loss, aux), grads = jax.value_and_grad(wm_loss_fn, has_aux=True)(
            (params["world_model"], jepa_online)
        )
        grads = pmean_tree(grads, axis)
        wm_updates, opt_states["world_model"] = optimizers["world_model"].update(
            grads, opt_states["world_model"], (params["world_model"], jepa_online)
        )
        (params["world_model"], jepa_online) = optax.apply_updates(
            (params["world_model"], jepa_online), wm_updates
        )
        params["jepa"]["projector"] = jepa_online["projector"]
        params["jepa"]["predictor"] = jepa_online["predictor"]

        # --- JEPA momentum update (reference :245-246) ---------------------
        params["jepa"]["target_encoder"] = optax.incremental_update(
            encoder_subtree(params["world_model"]), params["jepa"]["target_encoder"], 1 - ema_m
        )
        params["jepa"]["target_projector"] = optax.incremental_update(
            params["jepa"]["projector"], params["jepa"]["target_projector"], 1 - ema_m
        )

        # ---------------- BEHAVIOUR LEARNING (same as DV3) -----------------
        wm_params = cast_floating(params["world_model"], cdt)
        posteriors = jax.lax.stop_gradient(aux["posteriors"]).reshape(T * B, stoch_flat)
        recurrents = jax.lax.stop_gradient(aux["recurrents"]).reshape(T * B, recurrent_size)
        true_continue = (1 - batch["terminated"]).reshape(T * B, 1)

        def actor_loss_fn(actor_params, moments_state):
            actor_params = cast_floating(actor_params, cdt)
            latent0 = jnp.concatenate([posteriors, recurrents], axis=-1)
            a0 = actor_def.apply(actor_params, jax.lax.stop_gradient(latent0), k_img_actions, False, method="act")

            def img_body(carry, key_t):
                prior, recurrent, actions = carry
                k_dyn, k_act = jax.random.split(key_t)
                prior, recurrent = world_model_def.apply(
                    wm_params, prior, recurrent, actions, k_dyn, method="imagination"
                )
                latent = jnp.concatenate([prior, recurrent], axis=-1)
                actions = actor_def.apply(
                    actor_params, jax.lax.stop_gradient(latent), k_act, False, method="act"
                )
                return (prior, recurrent, actions), (latent, actions)

            keys_h = jax.random.split(k_img, horizon)
            _, (latents_h, actions_h) = jax.lax.scan(
                img_body, (posteriors, recurrents, a0), keys_h, unroll=scan_unroll
            )
            imagined_trajectories = jnp.concatenate([latent0[None], latents_h], axis=0)
            imagined_actions = jnp.concatenate([a0[None], actions_h], axis=0)

            predicted_values = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(params["critic"], cdt), imagined_trajectories), dims=1
            ).mean
            predicted_rewards = TwoHotEncodingDistribution(
                world_model_def.apply(wm_params, imagined_trajectories, method="reward_logits"), dims=1
            ).mean
            continues = Bernoulli(
                world_model_def.apply(wm_params, imagined_trajectories, method="continue_logits"),
                event_dims=1,
            ).mode
            continues = jnp.concatenate([true_continue[None], continues[1:]], axis=0)

            lambda_values = compute_lambda_values(
                predicted_rewards[1:], predicted_values[1:], continues[1:] * gamma, lmbda=cfg.algo.lmbda
            )
            discount = jnp.cumprod(continues * gamma, axis=0) / gamma
            discount = jax.lax.stop_gradient(discount)
            baseline = predicted_values[:-1]
            offset, invscale, new_moments = update_moments(
                moments_state,
                lambda_values,
                cfg.algo.actor.moments.decay,
                cfg.algo.actor.moments.max,
                cfg.algo.actor.moments.percentile.low,
                cfg.algo.actor.moments.percentile.high,
                axis_name=axis,
            )
            advantage = (lambda_values - offset) / invscale - (baseline - offset) / invscale
            log_probs, entropies = actor_def.apply(
                actor_params,
                jax.lax.stop_gradient(imagined_trajectories),
                jax.lax.stop_gradient(imagined_actions),
                method="log_prob_entropy",
            )
            if is_continuous:
                objective = advantage
            else:
                objective = log_probs[:-1] * jax.lax.stop_gradient(advantage)
            entropy = cfg.algo.actor.ent_coef * entropies
            policy_loss = -jnp.mean(discount[:-1] * (objective + entropy[:-1]))
            aux2 = {
                "imagined_trajectories": jax.lax.stop_gradient(imagined_trajectories),
                "lambda_values": jax.lax.stop_gradient(lambda_values),
                "discount": discount,
                "moments": new_moments,
            }
            return policy_loss, aux2

        (policy_loss, aux2), actor_grads = jax.value_and_grad(actor_loss_fn, has_aux=True)(
            params["actor"], moments_state
        )
        actor_grads = pmean_tree(actor_grads, axis)
        actor_updates, opt_states["actor"] = optimizers["actor"].update(
            actor_grads, opt_states["actor"], params["actor"]
        )
        params["actor"] = optax.apply_updates(params["actor"], actor_updates)
        moments_state = aux2["moments"]

        imagined_trajectories = aux2["imagined_trajectories"]
        lambda_values = aux2["lambda_values"]
        discount = aux2["discount"]

        def critic_loss_fn(critic_params):
            qv = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(critic_params, cdt), imagined_trajectories[:-1]), dims=1
            )
            predicted_target_values = TwoHotEncodingDistribution(
                critic_def.apply(cast_floating(params["target_critic"], cdt), imagined_trajectories[:-1]),
                dims=1,
            ).mean
            value_loss = -qv.log_prob(lambda_values)
            value_loss = value_loss - qv.log_prob(jax.lax.stop_gradient(predicted_target_values))
            return jnp.mean(value_loss * discount[:-1, ..., 0])

        value_loss, critic_grads = jax.value_and_grad(critic_loss_fn)(params["critic"])
        critic_grads = pmean_tree(critic_grads, axis)
        critic_updates, opt_states["critic"] = optimizers["critic"].update(
            critic_grads, opt_states["critic"], params["critic"]
        )
        params["critic"] = optax.apply_updates(params["critic"], critic_updates)

        metrics = jnp.stack(
            [
                aux["rec_loss"] + jepa_coef * aux["jepa_loss"],
                aux["observation_loss"],
                aux["reward_loss"],
                aux["state_loss"],
                aux["continue_loss"],
                aux["kl"],
                policy_loss,
                value_loss,
                optax.global_norm(grads[0]),
                optax.global_norm(actor_grads),
                optax.global_norm(critic_grads),
            ]
        )
        metrics = pmean_tree(metrics, axis)
        # learn-health stats: the JEPA heads are their own top-level module
        # (grads[1] / wm_updates[1] are the online projector+predictor); all
        # inputs are pmean'd/replicated so the dict rides the metric drain's
        # batched fetch unchanged across devices
        if health.enabled:
            hstats = health_stats(
                {
                    "world_model": grads[0],
                    "jepa": grads[1],
                    "actor": actor_grads,
                    "critic": critic_grads,
                },
                {
                    "world_model": wm_updates[0],
                    "jepa": wm_updates[1],
                    "actor": actor_updates,
                    "critic": critic_updates,
                },
                {
                    "world_model": params["world_model"],
                    "jepa": {
                        "projector": params["jepa"]["projector"],
                        "predictor": params["jepa"]["predictor"],
                    },
                    "actor": params["actor"],
                    "critic": params["critic"],
                },
                per_module=health.per_module,
                dead_eps=health.dead_eps,
            )
        else:
            hstats = {}
        if sentinel.skip_update:
            finite = jnp.all(jnp.isfinite(metrics))
            params, opt_states, moments_state = select_finite(
                finite, (params, opt_states, moments_state), prev_state
            )
        return params, opt_states, moments_state, metrics, hstats

    from sheeprl_tpu.parallel.dp import fsdp_min_shard_bytes

    return dp_jit(
        train_step,
        mesh,
        in_specs=(P(), P(), P(), batch_spec(batch_axis=1), P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        donate_argnums=(0, 1, 2),
        min_shard_bytes=fsdp_min_shard_bytes(cfg),
    )


def _extra_opt_setup(optimizers, opt_states, params):
    """The world optimizer also trains the JEPA projector/predictor
    (reference: jepa head is attached to the WorldModel module)."""
    jepa_online = {"projector": params["jepa"]["projector"], "predictor": params["jepa"]["predictor"]}
    opt_states["world_model"] = optimizers["world_model"].init((params["world_model"], jepa_online))
    return opt_states


@register_algorithm()
def main(runtime, cfg):
    return _dreamer_main(runtime, cfg, _build_agent, make_train_step, extra_opt_setup=_extra_opt_setup)
