"""DreamerV2 agent (reference /root/reference/sheeprl/algos/dreamer_v2/agent.py:31-1104).

Architecturally the DV3 stack (../dreamer_v3/agent.py) with the DV2 settings:
ELU activations, no LayerNorm except in the GRU, no unimix, zero (non-learned)
initial recurrent state, plain-scalar reward/critic heads (Normal(.,1) instead
of two-hot), no symlog on vector inputs, default torch-style inits, and the
`trunc_normal` continuous actor.  DV3 imports DV2's classes in the reference
(dreamer_v3/agent.py:24-25); here the sharing points the other way — the
parametric modules live in dreamer_v3/agent.py.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, Optional, Sequence

import gymnasium
import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.agent import (  # noqa: F401
    Actor,
    Critic,
    PlayerDV3,
    WorldModel,
    compute_stochastic_state,
    resolve_actor_cls,
)

PlayerDV2 = PlayerDV3  # same stateful env-interaction machinery (reference agent.py:735-838)


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    world_model_state: Optional[Dict[str, Any]] = None,
    actor_state: Optional[Dict[str, Any]] = None,
    critic_state: Optional[Dict[str, Any]] = None,
    target_critic_state: Optional[Dict[str, Any]] = None,
):
    """Returns (world_model_def, actor_def, critic_def, params)
    (reference agent.py:841-1104)."""
    wm_cfg = cfg.algo.world_model
    actor_cfg = cfg.algo.actor
    critic_cfg = cfg.algo.critic
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_decoder_keys = list(cfg.algo.cnn_keys.decoder)
    mlp_decoder_keys = list(cfg.algo.mlp_keys.decoder)
    image_size = tuple(obs_space[cnn_keys[0]].shape[-2:]) if cnn_keys else (64, 64)
    cnn_stages = int(np.log2(cfg.env.screen_size) - np.log2(4)) if cnn_keys else 4
    recurrent_state_size = wm_cfg.recurrent_model.recurrent_state_size
    stochastic_size = wm_cfg.stochastic_size
    discrete_size = wm_cfg.discrete_size
    latent_state_size = stochastic_size * discrete_size + recurrent_state_size
    layer_norm = bool(cfg.algo.layer_norm)

    world_model_def = WorldModel(
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        cnn_decoder_keys=tuple(cnn_decoder_keys),
        mlp_decoder_keys=tuple(mlp_decoder_keys),
        mlp_output_dims=tuple(int(prod(obs_space[k].shape)) for k in mlp_decoder_keys),
        cnn_input_channels=tuple(int(prod(obs_space[k].shape[:-2])) for k in cnn_decoder_keys),
        image_size=image_size,
        channels_multiplier=wm_cfg.encoder.cnn_channels_multiplier,
        cnn_stages=cnn_stages,
        encoder_dense_units=wm_cfg.encoder.dense_units,
        encoder_mlp_layers=wm_cfg.encoder.mlp_layers,
        decoder_dense_units=wm_cfg.observation_model.dense_units,
        decoder_mlp_layers=wm_cfg.observation_model.mlp_layers,
        recurrent_state_size=recurrent_state_size,
        stochastic_size=stochastic_size,
        discrete_size=discrete_size,
        rssm_dense_units=wm_cfg.recurrent_model.dense_units,
        rssm_hidden_size=wm_cfg.representation_model.hidden_size,
        reward_dense_units=wm_cfg.reward_model.dense_units,
        reward_mlp_layers=wm_cfg.reward_model.mlp_layers,
        reward_bins=1,  # plain Normal(.,1) scalar head (reference dreamer_v2.py:170)
        continue_dense_units=wm_cfg.discount_model.dense_units,
        continue_mlp_layers=wm_cfg.discount_model.mlp_layers,
        unimix=0.0,
        eps=1e-5,
        learnable_initial_recurrent_state=False,
        decoupled_rssm=False,
        dense_act="elu",
        cnn_act="elu",
        layer_norm=layer_norm,
        gru_layer_norm=bool(wm_cfg.recurrent_model.layer_norm),
        symlog_inputs=False,
        hafner_heads=False,
    )
    # reference dv1 agent.py:472 / dv2 agent.py:1019: actor class from config
    actor_def = resolve_actor_cls(cfg.algo.actor)(
        latent_state_size=latent_state_size,
        actions_dim=tuple(int(a) for a in actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.type,
        init_std=actor_cfg.init_std,
        min_std=actor_cfg.min_std,
        dense_units=actor_cfg.dense_units,
        mlp_layers=actor_cfg.mlp_layers,
        unimix=0.0,
        action_clip=1.0,
        eps=1e-5,
        dense_act="elu",
        layer_norm=layer_norm,
        default_continuous_dist="trunc_normal",
    )
    critic_def = Critic(
        dense_units=critic_cfg.dense_units,
        mlp_layers=critic_cfg.mlp_layers,
        bins=1,
        eps=1e-5,
        act="elu",
        layer_norm=layer_norm,
        zero_init_head=False,
    )

    key = jax.random.PRNGKey(int(cfg.seed or 0))
    k_wm, k_actor, k_critic, k_call = jax.random.split(key, 4)
    sample_obs: Dict[str, jax.Array] = {}
    for k in cnn_keys:
        sample_obs[k] = jnp.zeros((1,) + tuple(obs_space[k].shape), jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, int(prod(obs_space[k].shape))), jnp.float32)
    sample_action = jnp.zeros((1, int(sum(actions_dim))), jnp.float32)
    sample_is_first = jnp.ones((1, 1), jnp.float32)
    wm_params = world_model_def.init(k_wm, sample_obs, sample_action, sample_is_first, k_call)
    sample_latent = jnp.zeros((1, latent_state_size), jnp.float32)
    actor_params = actor_def.init(k_actor, sample_latent)
    critic_params = critic_def.init(k_critic, sample_latent)
    params = {
        "world_model": wm_params,
        "actor": actor_params,
        "critic": critic_params,
        "target_critic": jax.tree_util.tree_map(jnp.copy, critic_params),
    }
    if world_model_state is not None:
        params["world_model"] = jax.tree_util.tree_map(jnp.asarray, world_model_state)
    if actor_state is not None:
        params["actor"] = jax.tree_util.tree_map(jnp.asarray, actor_state)
    if critic_state is not None:
        params["critic"] = jax.tree_util.tree_map(jnp.asarray, critic_state)
    if target_critic_state is not None:
        params["target_critic"] = jax.tree_util.tree_map(jnp.asarray, target_critic_state)
    return world_model_def, actor_def, critic_def, params
