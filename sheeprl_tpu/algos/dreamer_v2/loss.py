"""DreamerV2 world-model loss (reference /root/reference/sheeprl/algos/dreamer_v2/loss.py):
Normal(.,1) observation/reward log-probs, alpha-form KL balancing (0.8) with
free-nats applied to the batch mean (``kl_free_avg``)."""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from sheeprl_tpu.ops.distributions import Bernoulli, kl_categorical


def normal_log_prob(mean: jax.Array, value: jax.Array, event_dims: int) -> jax.Array:
    """Independent(Normal(mean, 1)) log-prob summed over trailing event dims.
    Computed in fp32 regardless of input dtype (mixed-precision loss boundary)."""
    mean = mean.astype(jnp.float32)
    value = value.astype(jnp.float32)
    lp = -0.5 * (value - mean) ** 2 - 0.5 * jnp.log(2 * jnp.pi)
    return jnp.sum(lp, axis=tuple(range(-event_dims, 0)))


def reconstruction_loss(
    recon: Dict[str, jax.Array],
    observations: Dict[str, jax.Array],
    reward_mean: jax.Array,
    rewards: jax.Array,
    priors_logits: jax.Array,
    posteriors_logits: jax.Array,
    kl_balancing_alpha: float = 0.8,
    kl_free_nats: float = 1.0,
    kl_free_avg: bool = True,
    kl_regularizer: float = 1.0,
    pc: Optional[Bernoulli] = None,
    continue_targets: Optional[jax.Array] = None,
    discount_scale_factor: float = 1.0,
) -> Tuple[jax.Array, ...]:
    observation_loss = -sum(
        jnp.mean(normal_log_prob(recon[k], observations[k], len(recon[k].shape[2:]))) for k in recon
    )
    reward_loss = -jnp.mean(normal_log_prob(reward_mean, rewards, 1))
    lhs = kl = kl_categorical(jax.lax.stop_gradient(posteriors_logits), priors_logits, event_dims=1)
    rhs = kl_categorical(posteriors_logits, jax.lax.stop_gradient(priors_logits), event_dims=1)
    if kl_free_avg:
        lhs_m, rhs_m = jnp.mean(lhs), jnp.mean(rhs)
        loss_lhs = jnp.maximum(lhs_m, kl_free_nats)
        loss_rhs = jnp.maximum(rhs_m, kl_free_nats)
    else:
        loss_lhs = jnp.mean(jnp.maximum(lhs, kl_free_nats))
        loss_rhs = jnp.mean(jnp.maximum(rhs, kl_free_nats))
    kl_loss = kl_balancing_alpha * loss_lhs + (1 - kl_balancing_alpha) * loss_rhs
    if pc is not None and continue_targets is not None:
        continue_loss = discount_scale_factor * -jnp.mean(pc.log_prob(continue_targets))
    else:
        continue_loss = jnp.zeros_like(reward_loss)
    rec_loss = kl_regularizer * kl_loss + observation_loss + reward_loss + continue_loss
    return rec_loss, jnp.mean(kl), kl_loss, reward_loss, observation_loss, continue_loss
