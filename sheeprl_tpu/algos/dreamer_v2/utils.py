"""DreamerV2 helpers (reference /root/reference/sheeprl/algos/dreamer_v2/utils.py)."""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs, test  # noqa: F401  (same obs/test machinery)

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/world_model_loss",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/observation_loss",
    "Loss/reward_loss",
    "Loss/state_loss",
    "Loss/continue_loss",
    "State/kl",
    "State/post_entropy",
    "State/prior_entropy",
    "Grads/world_model",
    "Grads/actor",
    "Grads/critic",
}
MODELS_TO_REGISTER = {"world_model", "actor", "critic", "target_critic"}


def compute_lambda_values(
    rewards: jax.Array,
    values: jax.Array,
    continues: jax.Array,
    bootstrap: jax.Array | None = None,
    horizon: int = 15,
    lmbda: float = 0.95,
) -> jax.Array:
    """DV2-style lambda returns with explicit bootstrap (reference
    utils.py:85-102) as a reverse scan."""
    if bootstrap is None:
        bootstrap = jnp.zeros_like(values[-1:])
    next_values = jnp.concatenate([values[1:], bootstrap], axis=0)
    inputs = rewards + continues * next_values * (1 - lmbda)

    def body(agg, inp):
        inp_t, cont_t = inp
        agg = inp_t + cont_t * lmbda * agg
        return agg, agg

    _, lv = jax.lax.scan(body, bootstrap[0], (inputs, continues), reverse=True)
    return lv
