"""PPO agent: encoder + actor + critic as one flax module.

Behavioral equivalent of /root/reference/sheeprl/algos/ppo/agent.py:20-369,
redesigned functionally for TPU: the agent is a pure ``init/apply`` module over
a params pytree; there is no DDP wrapper and no separate "player" copy with
tied weights (reference agent.py:369-430) — the player simply applies the same
params, which are values, not objects.

Action-space handling (reference agent.py:92-200):
- continuous (``normal``/``tanh_normal``): one head emitting mean and log-std;
- discrete / multi-discrete: one logits head per action sub-space.
"""

from __future__ import annotations

from math import prod
from typing import Any, Dict, List, Optional, Sequence, Tuple

import gymnasium
import jax
import jax.numpy as jnp
from flax import linen as nn

from sheeprl_tpu.models.blocks import MLP, NatureCNN, cnn_forward
from sheeprl_tpu.ops.distributions import Categorical, Normal, TanhNormal


class _CNNEncoder(nn.Module):
    """NatureCNN over the channel-concat of pixel keys (reference agent.py:20-36)."""

    features_dim: int
    keys: Sequence[str]

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-3) / 255.0
        return cnn_forward(NatureCNN(features_dim=self.features_dim), x)


class _MLPEncoder(nn.Module):
    """Dense encoder over the feature-concat of vector keys (reference agent.py:39-70)."""

    keys: Sequence[str]
    features_dim: Optional[int]
    dense_units: int = 64
    mlp_layers: int = 2
    dense_act: str = "tanh"
    layer_norm: bool = False

    @nn.compact
    def __call__(self, obs: Dict[str, jax.Array]) -> jax.Array:
        x = jnp.concatenate([obs[k] for k in self.keys], axis=-1)
        if self.mlp_layers == 0:
            return x
        return MLP(
            hidden_sizes=[self.dense_units] * self.mlp_layers,
            output_dim=self.features_dim,
            activation=self.dense_act,
            layer_norm=self.layer_norm,
        )(x)


class PPOAgent(nn.Module):
    """Feature extractor + actor heads + critic (reference agent.py:92-366)."""

    actions_dim: Sequence[int]
    is_continuous: bool
    distribution: str = "auto"
    cnn_keys: Sequence[str] = ()
    mlp_keys: Sequence[str] = ()
    mlp_input_dim: int = 0
    encoder_cfg: Any = None
    actor_cfg: Any = None
    critic_cfg: Any = None

    def setup(self) -> None:
        dist = self.distribution.lower()
        if dist not in ("auto", "normal", "tanh_normal", "discrete"):
            raise ValueError(
                f"The distribution must be one of: `auto`, `discrete`, `normal` and `tanh_normal`. Found: {dist}"
            )
        if dist == "discrete" and self.is_continuous:
            raise ValueError("You have chosen a discrete distribution but `is_continuous` is true")
        if dist in ("normal", "tanh_normal") and not self.is_continuous:
            raise ValueError("You have chosen a continuous distribution but `is_continuous` is false")
        self.dist = ("normal" if self.is_continuous else "discrete") if dist == "auto" else dist

        enc = self.encoder_cfg
        self._cnn_enc = (
            _CNNEncoder(features_dim=enc["cnn_features_dim"], keys=tuple(self.cnn_keys)) if self.cnn_keys else None
        )
        self._mlp_enc = (
            _MLPEncoder(
                keys=tuple(self.mlp_keys),
                features_dim=enc["mlp_features_dim"],
                dense_units=enc["dense_units"],
                mlp_layers=enc["mlp_layers"],
                dense_act=enc["dense_act"],
                layer_norm=enc["layer_norm"],
            )
            if self.mlp_keys
            else None
        )
        a = self.actor_cfg
        self.actor_backbone = MLP(
            hidden_sizes=[a["dense_units"]] * a["mlp_layers"],
            activation=a["dense_act"],
            layer_norm=a["layer_norm"],
        )
        if self.is_continuous:
            self.actor_heads = [nn.Dense(sum(self.actions_dim) * 2)]
        else:
            self.actor_heads = [nn.Dense(d) for d in self.actions_dim]
        c = self.critic_cfg
        self.critic = MLP(
            hidden_sizes=[c["dense_units"]] * c["mlp_layers"],
            output_dim=1,
            activation=c["dense_act"],
            layer_norm=c["layer_norm"],
        )

    def _features(self, obs: Dict[str, jax.Array]) -> jax.Array:
        feats = []
        if self._cnn_enc is not None:
            feats.append(self._cnn_enc(obs))
        if self._mlp_enc is not None:
            feats.append(self._mlp_enc(obs))
        return jnp.concatenate(feats, axis=-1) if len(feats) > 1 else feats[0]

    def __call__(
        self,
        obs: Dict[str, jax.Array],
        key: Optional[jax.Array] = None,
        actions: Optional[jax.Array] = None,
        greedy: bool = False,
    ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """Return ``(actions, log_prob, entropy, value)``.

        When ``actions`` is given, evaluates their log-prob/entropy (train
        path, reference agent.py:202-263); otherwise samples with ``key``
        (rollout path) or takes the mode (``greedy``, test path).
        """
        feat = self._features(obs)
        value = self.critic(feat)
        pre = self.actor_backbone(feat)
        outs = [head(pre) for head in self.actor_heads]
        if self.is_continuous:
            mean, log_std = jnp.split(outs[0], 2, axis=-1)
            std = jnp.exp(log_std)
            if self.dist == "tanh_normal":
                dist = TanhNormal(mean, std, event_dims=1)
            else:
                dist = Normal(mean, std, event_dims=1)
            if actions is None:
                actions = dist.mode if greedy else dist.rsample(key)
            log_prob = dist.log_prob(actions)
            if self.dist == "tanh_normal":
                # tanh-normal entropy has no closed form; use -log_prob of the sample
                entropy = -log_prob
            else:
                entropy = dist.entropy()
            return actions, log_prob, entropy, value
        # discrete / multi-discrete: one categorical per sub-action
        sampled: List[jax.Array] = []
        log_probs: List[jax.Array] = []
        entropies: List[jax.Array] = []
        split_actions = (
            jnp.split(actions, len(self.actions_dim), axis=-1) if actions is not None else [None] * len(outs)
        )
        for i, logits in enumerate(outs):
            dist = Categorical(logits=logits)
            if split_actions[i] is None:
                if greedy:
                    act_idx = jnp.argmax(logits, axis=-1)
                else:
                    sub_key = jax.random.fold_in(key, i)
                    act_idx = dist.sample(sub_key)
                act = act_idx[..., None].astype(jnp.float32)
            else:
                act = split_actions[i]
                act_idx = act[..., 0].astype(jnp.int32)
            sampled.append(act)
            log_probs.append(dist.log_prob(act_idx)[..., None])
            entropies.append(dist.entropy()[..., None])
        return (
            jnp.concatenate(sampled, axis=-1),
            jnp.sum(jnp.concatenate(log_probs, axis=-1), axis=-1, keepdims=True),
            jnp.sum(jnp.concatenate(entropies, axis=-1), axis=-1, keepdims=True),
            value,
        )

    def get_values(self, obs: Dict[str, jax.Array]) -> jax.Array:
        return self.critic(self._features(obs))


def build_agent(
    runtime,
    actions_dim: Sequence[int],
    is_continuous: bool,
    cfg,
    obs_space: gymnasium.spaces.Dict,
    agent_state: Optional[Dict[str, Any]] = None,
):
    """Create the agent module + its params (reference agent.py:369-430).

    Returns ``(agent_module, params, sample_obs)``.  ``sample_obs`` is a dict
    of zero arrays used to (re)trace jitted applies.
    """
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_input_dim = int(sum(prod(obs_space[k].shape) for k in mlp_keys))
    agent = PPOAgent(
        actions_dim=tuple(int(a) for a in actions_dim),
        is_continuous=is_continuous,
        distribution=cfg.distribution.type,
        cnn_keys=tuple(cnn_keys),
        mlp_keys=tuple(mlp_keys),
        mlp_input_dim=mlp_input_dim,
        encoder_cfg=cfg.algo.encoder,
        actor_cfg=cfg.algo.actor,
        critic_cfg=cfg.algo.critic,
    )
    sample_obs = {}
    for k in cnn_keys:
        sample_obs[k] = jnp.zeros((1,) + tuple(obs_space[k].shape), dtype=jnp.float32)
    for k in mlp_keys:
        sample_obs[k] = jnp.zeros((1, prod(obs_space[k].shape)), dtype=jnp.float32)
    params = agent.init(jax.random.PRNGKey(int(cfg.seed or 0)), sample_obs, key=jax.random.PRNGKey(0))
    if agent_state is not None:
        params = jax.tree_util.tree_map(jnp.asarray, agent_state)
    return agent, params, sample_obs
