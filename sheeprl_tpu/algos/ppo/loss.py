"""PPO losses (reference /root/reference/sheeprl/algos/ppo/loss.py).

Pure functions of arrays — designed to be called inside the jitted train step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce(x: jax.Array, reduction: str) -> jax.Array:
    if reduction == "mean":
        return jnp.mean(x)
    if reduction == "sum":
        return jnp.sum(x)
    if reduction == "none":
        return x
    raise ValueError(f"Unrecognized reduction: {reduction}")


def policy_loss(
    new_logprobs: jax.Array,
    old_logprobs: jax.Array,
    advantages: jax.Array,
    clip_coef: float | jax.Array,
    reduction: str = "mean",
) -> jax.Array:
    """Clipped-surrogate policy loss (reference loss.py:9-38)."""
    logratio = new_logprobs - old_logprobs
    ratio = jnp.exp(logratio)
    pg_loss1 = -advantages * ratio
    pg_loss2 = -advantages * jnp.clip(ratio, 1 - clip_coef, 1 + clip_coef)
    return _reduce(jnp.maximum(pg_loss1, pg_loss2), reduction)


def value_loss(
    new_values: jax.Array,
    old_values: jax.Array,
    returns: jax.Array,
    clip_coef: float | jax.Array,
    clip_vloss: bool,
    reduction: str = "mean",
) -> jax.Array:
    """Value loss, optionally clipped around the rollout values
    (reference loss.py:41-66)."""
    if not clip_vloss:
        return _reduce(0.5 * (new_values - returns) ** 2, reduction)
    v_loss_unclipped = (new_values - returns) ** 2
    v_clipped = old_values + jnp.clip(new_values - old_values, -clip_coef, clip_coef)
    v_loss_clipped = (v_clipped - returns) ** 2
    return _reduce(0.5 * jnp.maximum(v_loss_unclipped, v_loss_clipped), reduction)


def entropy_loss(entropy: jax.Array, reduction: str = "mean") -> jax.Array:
    """Negative entropy bonus (reference loss.py:69-76)."""
    return _reduce(-entropy, reduction)
