"""PPO training loop — TPU-native re-design of
/root/reference/sheeprl/algos/ppo/ppo.py:30-453.

Shape of the redesign (SURVEY §7):
- The reference runs one process per device (Fabric DDP) with a Python
  minibatch loop and per-minibatch gradient all-reduce.  Here a single
  controller drives every chip: the **whole update phase** (epochs ×
  minibatches) is one jitted ``lax.scan`` graph, data-parallel over the mesh
  via ``shard_map`` with an in-graph ``pmean`` on gradients — the TPU ICI
  equivalent of DDP's NCCL all-reduce.
- Rollouts run on the host (gymnasium vector envs); the policy forward per env
  step is one small jit; observations transfer uint8 and are normalized on
  device.
- GAE is a reverse ``lax.scan`` (ops/numerics.py) instead of the reference's
  reversed Python loop (utils/utils.py:63-103).
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.loss import entropy_loss, policy_loss, value_loss
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.envs.player import fetch_values, obs_sharding
from sheeprl_tpu.ops.numerics import gae
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import get_diagnostics, polynomial_decay, save_configs


def make_train_step(agent, optimizer, cfg, mesh, num_minibatches: int, batch_size: int):
    """Build the jitted update: (params, opt_state, data, key, coefs) ->
    (params, opt_state, metrics).

    ``data`` leaves are ``[N_local * world, ...]`` host-sharded along the
    mesh's ``data`` axis.  Each device permutes its local shard per epoch (the
    reference's per-rank RandomSampler, ppo.py:57-65) and gradients are
    ``pmean``-ed per minibatch (DDP all-reduce equivalent).

    ``metrics`` is ``[pg_loss, v_loss, e_loss, grad_norm, nonfinite_steps]``:
    the diagnostics sentinel's finiteness flag and the global grad norm ride
    the existing metric fetch, and under
    ``diagnostics.sentinel.policy=skip_update`` a non-finite minibatch update
    is discarded in-graph (params/opt state keep their pre-step values).

    With ``diagnostics.health`` on (the default) the step also returns a
    learn-health stats dict (``health_stats``: per-module grad/update/param
    norms, update/weight ratio, dead-unit fraction, plus the value-function
    explained variance) that rides the same output fetch — the global grad
    norm is computed ONCE there and shared with the sentinel's finiteness
    check.  Disabled, the fourth output is an empty dict and the graph is
    unchanged.
    """
    from sheeprl_tpu.diagnostics.health import explained_variance, health_spec, health_stats
    from sheeprl_tpu.diagnostics.sentinel import finite_flag, select_finite, sentinel_spec

    sentinel = sentinel_spec(cfg)
    health = health_spec(cfg)
    world = mesh.devices.size
    distributed = world > 1
    cdt = compute_dtype_of(cfg)  # bf16 under fabric.precision=bf16-*

    def loss_fn(params, batch, clip_coef, ent_coef, vf_coef):
        _, new_logprobs, entropy, new_values = agent.apply(
            cast_floating(params, cdt), cast_floating(batch["obs"], cdt), actions=batch["actions"]
        )
        new_values = new_values.astype(jnp.float32)  # loss math in fp32
        advantages = batch["advantages"]
        if cfg.algo.normalize_advantages:
            mu = advantages.mean()
            std = advantages.std()
            if distributed:
                mu = jax.lax.pmean(mu, "data")
                std = jax.lax.pmean(std, "data")
            advantages = (advantages - mu) / (std + 1e-8)
        pg_loss = policy_loss(
            new_logprobs, batch["logprobs"], advantages, clip_coef, cfg.algo.loss_reduction
        )
        v_loss = value_loss(
            new_values,
            batch["values"],
            batch["returns"],
            clip_coef,
            cfg.algo.clip_vloss,
            cfg.algo.loss_reduction,
        )
        e_loss = entropy_loss(entropy, cfg.algo.loss_reduction)
        total = pg_loss + vf_coef * v_loss + ent_coef * e_loss
        return total, (pg_loss, v_loss, e_loss)

    def update(params, opt_state, data, key, coefs):
        clip_coef, ent_coef, vf_coef = coefs
        n_local = num_minibatches * batch_size

        def epoch_body(carry, epoch_key):
            params, opt_state = carry
            perm = jax.random.permutation(epoch_key, n_local)
            idxs = perm.reshape(num_minibatches, batch_size)

            def mb_body(carry, mb_idx):
                params, opt_state = carry
                mb = jax.tree_util.tree_map(lambda x: x[mb_idx], data)
                grads, aux = jax.grad(loss_fn, has_aux=True)(
                    params, mb, clip_coef, ent_coef, vf_coef
                )
                if distributed:
                    grads = jax.lax.pmean(grads, "data")
                    aux = jax.lax.pmean(aux, "data")
                updates, new_opt_state = optimizer.update(grads, opt_state, params)
                new_params = optax.apply_updates(params, updates)
                # any NaN/Inf gradient leaf poisons the global norm, so one
                # scalar check covers the whole tree; pmean'd inputs mean
                # every device takes the same branch of the select below.
                # With health on, the norm comes from health_stats — one
                # whole-tree reduction shared by sentinel + health gauges.
                if health.enabled:
                    hstats = health_stats(
                        grads, updates, params, per_module=health.per_module, dead_eps=health.dead_eps
                    )
                    gnorm = hstats["grad_norm"]
                else:
                    hstats = {}
                    gnorm = optax.global_norm(grads)
                finite = finite_flag(gnorm, *aux)
                if sentinel.skip_update:
                    params = select_finite(finite, new_params, params)
                    opt_state = select_finite(finite, new_opt_state, opt_state)
                else:
                    params, opt_state = new_params, new_opt_state
                stats = jnp.stack([*aux, gnorm, 1.0 - finite.astype(jnp.float32)])
                return (params, opt_state), (stats, hstats)

            return jax.lax.scan(mb_body, (params, opt_state), idxs)

        keys = jax.random.split(key, cfg.algo.update_epochs)
        (params, opt_state), (losses, health_tree) = jax.lax.scan(
            epoch_body, (params, opt_state), keys
        )
        flat = losses.reshape(-1, 5)
        # mean losses/grad-norm over minibatches; nonfinite steps are a count
        metrics = jnp.concatenate([jnp.mean(flat[:, :4], axis=0), jnp.sum(flat[:, 4:], axis=0)])
        # health stats average over epochs x minibatches and ride the same
        # output fetch; value EV is whole-batch (pre-update critic vs returns)
        health_out = jax.tree_util.tree_map(jnp.mean, health_tree)
        if health.enabled:
            ev = explained_variance(data["values"], data["returns"])
            if distributed:
                ev = jax.lax.pmean(ev, "data")
            health_out["value_ev"] = ev
        return params, opt_state, metrics, health_out

    if distributed:
        from sheeprl_tpu.parallel.compat import shard_map

        def sharded_update(params, opt_state, data, key, coefs):
            # per-device independent permutation: fold the axis index into the key
            def body(params, opt_state, data, key, coefs):
                key = jax.random.fold_in(key, jax.lax.axis_index("data"))
                return update(params, opt_state, data, key, coefs)

            return shard_map(
                body,
                mesh=mesh,
                in_specs=(P(), P(), P("data"), P(), P()),
                out_specs=(P(), P(), P(), P()),
                check_vma=False,
            )(params, opt_state, data, key, coefs)

        return jax.jit(sharded_update, donate_argnums=(0, 1))
    return jax.jit(update, donate_argnums=(0, 1))


@register_algorithm()
def main(runtime, cfg):
    # ---- sizes & validation (reference ppo.py:110-135) -------------------
    world_size = runtime.world_size
    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    batch_size = cfg.algo.per_rank_batch_size
    total_local = rollout_steps * num_envs
    if total_local % world_size != 0:
        raise ValueError(
            f"rollout_steps*num_envs ({total_local}) must be divisible by the number of devices ({world_size})"
        )
    n_per_device = total_local // world_size
    if batch_size is None or batch_size <= 0:
        raise ValueError(f"per_rank_batch_size must be a positive integer, got {batch_size}")
    if n_per_device % batch_size != 0:
        raise ValueError(
            f"Per-device rollout ({n_per_device}) must be divisible by per_rank_batch_size ({batch_size})"
        )
    num_minibatches = n_per_device // batch_size

    rng_key = runtime.seed_everything(cfg.seed)

    # ---- logger / metrics (reference ppo.py:129-166) ---------------------
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    # ---- envs (reference ppo.py:137-150; split-phase pipeline layer) -----
    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = cfg.algo.cnn_keys.encoder
    mlp_keys = cfg.algo.mlp_keys.encoder
    obs_keys = list(cnn_keys) + list(mlp_keys)
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    # ---- agent + optimizer (reference ppo.py:168-205) --------------------
    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    agent, params, _ = build_agent(
        runtime,
        actions_dim,
        is_continuous,
        cfg,
        observation_space,
        state["agent"] if state else None,
    )
    # bf16-true: weights live in bf16; *-mixed keeps fp32 masters, casting per-loss
    params = cast_floating(params, runtime.param_dtype)
    # lr annealing: bake a linear schedule into the optimizer's own step count
    # (reference anneals per-update on the host, ppo.py:230-263,415-424)
    policy_steps_per_iter = int(num_envs * rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    if cfg.algo.anneal_lr:
        schedule = optax.linear_schedule(
            init_value=cfg.algo.optimizer.learning_rate,
            end_value=0.0,
            transition_steps=max(1, total_iters * cfg.algo.update_epochs * num_minibatches),
        )
        base_opt = instantiate(cfg.algo.optimizer, learning_rate=schedule)
    else:
        base_opt = instantiate(cfg.algo.optimizer)
    chain = []
    if cfg.algo.max_grad_norm and cfg.algo.max_grad_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.algo.max_grad_norm))
    chain.append(base_opt)
    optimizer = optax.chain(*chain)
    opt_state = optimizer.init(params)
    if state and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_state,
            state["opt_state"],
        )

    # replicate params across the mesh (single-controller "DDP broadcast")
    from sheeprl_tpu.parallel.mesh import batch_sharding, replicated_sharding

    if world_size > 1:
        params = jax.device_put(params, replicated_sharding(runtime.mesh))
        opt_state = jax.device_put(opt_state, replicated_sharding(runtime.mesh))
        data_sharding = batch_sharding(runtime.mesh)
    else:
        data_sharding = None

    # telemetry instrumentation (recompile watchdog + cost_analysis FLOPs for
    # MFU): the train step dispatches through the AOT-compiled executable,
    # the rollout policy keeps native jit dispatch with signature watching
    train_step = diag.instrument(
        "train_step",
        make_train_step(agent, optimizer, cfg, runtime.mesh, num_minibatches, batch_size),
        kind="train",
        donate_argnums=(0, 1),  # params, opt_state — audited at first dispatch
    )
    diag.register_footprint("params", params)
    diag.register_footprint("opt_state", opt_state)

    # jitted rollout policy + value bootstrap
    @jax.jit
    def policy_step(params, obs, key):
        actions, logprobs, _, values = agent.apply(params, obs, key=key)
        return actions, logprobs, values

    policy_step = diag.instrument("policy_step", policy_step, kind="rollout")
    # device-resident batched inference: the obs slab is staged through ONE
    # device_put against this reused sharding, and all three policy outputs
    # come back in ONE blocking fetch — the per-step link cost is constant in
    # num_envs (fetch amortization = num_envs, emitted live by telemetry)
    stage_sharding = obs_sharding(runtime.mesh if world_size > 1 else None)

    @jax.jit
    def value_step(params, obs):
        return agent.apply(params, obs, method="get_values")

    @jax.jit
    def gae_step(params, last_obs, rewards, values, dones):
        next_value = agent.apply(params, last_obs, method="get_values")
        return gae(
            rewards,
            values,
            dones,
            next_value,
            rollout_steps,
            cfg.algo.gamma,
            cfg.algo.gae_lambda,
        )

    # ---- buffer (reference ppo.py:207-215) -------------------------------
    buffer_size = cfg.buffer.size
    rb = ReplayBuffer(
        buffer_size,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=obs_keys,
    )
    diag.track_buffer("replay", rb)

    # ---- counters (reference ppo.py:217-263) -----------------------------
    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0

    # clip/entropy coefficient annealing state (reference ppo.py:230-263)
    initial_ent = cfg.algo.ent_coef
    initial_clip = cfg.algo.clip_coef
    ent_coef = initial_ent
    clip_coef = initial_clip

    obs, _ = envs.reset(seed=cfg.seed)

    for iter_num in range(start_iter, total_iters + 1):
        with timer("Time/env_interaction_time"), diag.span("rollout"):
            for _ in range(rollout_steps):
                policy_step_count += num_envs  # global env steps (num_envs spans the whole mesh)
                diag.note_env_steps(num_envs)
                # sample actions (device): one staged h2d, one blocking fetch
                rng_key, step_key = jax.random.split(rng_key)
                torch_obs = prepare_obs(
                    obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs, sharding=stage_sharding
                )
                actions, logprobs, values = policy_step(params, torch_obs, step_key)
                actions_np, logprobs_np, values_np = fetch_values(actions, logprobs, values)
                if is_continuous:
                    env_actions = actions_np.reshape(num_envs, -1)
                elif is_multidiscrete:
                    env_actions = actions_np.astype(np.int64)
                else:
                    env_actions = actions_np[:, 0].astype(np.int64)

                # split-phase: the env workers step while this process copies
                # the policy outputs + current obs into the step record — the
                # per-step critical path is max(env_step, host copies) instead
                # of their sum (trajectories are bit-for-bit the serialized
                # order's: nothing the env sees changed, only when we wait)
                with diag.span("env_step_async"):
                    envs.step_async(env_actions)
                step_data: Dict[str, np.ndarray] = step_slab(
                    num_envs,
                    {
                        **{k: obs[k] for k in obs_keys},
                        "actions": actions_np,
                        "logprobs": logprobs_np,
                        "values": values_np,
                    },
                )
                with diag.span("env_wait"):
                    next_obs, rewards, terminated, truncated, info = envs.step_wait()
                dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)
                if cfg.env.clip_rewards:
                    rewards = np.tanh(rewards)

                # truncation bootstrapping (reference ppo.py:287-306)
                if "final_obs" in info and np.any(truncated):
                    final_obs = info["final_obs"]
                    trunc_idx = np.nonzero(truncated)[0]
                    stacked = {
                        k: np.stack([np.asarray(final_obs[i][k]) for i in trunc_idx])
                        for k in obs_keys
                    }
                    t_obs = prepare_obs(stacked, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=len(trunc_idx))
                    vals = np.asarray(value_step(params, t_obs))
                    rewards[trunc_idx] += cfg.algo.gamma * vals.reshape(-1, 1)

                step_data.update(step_slab(num_envs, {"rewards": rewards, "dones": dones}))
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                # episode stats (reference ppo.py:327-341)
                if "final_info" in info and "episode" in info["final_info"]:
                    ep = info["final_info"]["episode"]
                    mask = ep.get("_r", info["final_info"].get("_episode"))
                    if mask is not None and np.any(mask):
                        for r, l in zip(ep["r"][mask], ep["l"][mask]):
                            aggregator.update("Rewards/rew_avg", float(r))
                            aggregator.update("Game/ep_len_avg", float(l))

                obs = next_obs

        # ---- GAE over the collected rollout (reference ppo.py:344-360) ----
        with diag.span("buffer-sample"):
            local = {k: np.asarray(rb[k][:rollout_steps]) for k in rb.buffer.keys()}
            torch_last_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
            returns, advantages = gae_step(
                params,
                torch_last_obs,
                jnp.asarray(local["rewards"]),
                jnp.asarray(local["values"]),
                jnp.asarray(local["dones"]),
            )
            local["returns"] = np.asarray(returns)
            local["advantages"] = np.asarray(advantages)

            # flatten [T, N, ...] -> [T*N, ...]; device-shard along the data axis
            flat = {
                "obs": {k: local[k].reshape(total_local, *local[k].shape[2:]) for k in obs_keys},
                "actions": local["actions"].reshape(total_local, -1),
                "logprobs": local["logprobs"].reshape(total_local, -1),
                "values": local["values"].reshape(total_local, -1),
                "returns": local["returns"].reshape(total_local, -1),
                "advantages": local["advantages"].reshape(total_local, -1),
            }
            device_data = jax.tree_util.tree_map(
                lambda x: jax.device_put(jnp.asarray(x), data_sharding) if data_sharding else jnp.asarray(x),
                flat,
            )
        device_data = diag.maybe_inject_nan(iter_num, device_data)
        # recompile-watchdog drill: pads world_size rows that the minibatch
        # indexing never reads (training math unchanged, graph recompiles)
        device_data = diag.maybe_inject_shape_change(iter_num, device_data, pad=world_size)

        # ---- annealing (reference ppo.py:415-424) -------------------------
        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                iter_num, initial=initial_clip, final=0.0, max_decay_steps=total_iters, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                iter_num, initial=initial_ent, final=0.0, max_decay_steps=total_iters, power=1.0
            )

        # ---- update phase: one jitted graph (reference ppo.py:30-102) -----
        with timer("Time/train_time"), diag.span("train"):
            rng_key, train_key = jax.random.split(rng_key)
            coefs = (
                jnp.asarray(clip_coef, jnp.float32),
                jnp.asarray(ent_coef, jnp.float32),
                jnp.asarray(cfg.algo.vf_coef, jnp.float32),
            )
            params, opt_state, losses, health = train_step(
                params, opt_state, device_data, train_key, coefs
            )
            # ONE blocking d2h for metrics + health stats together: the
            # health tree rides the fetch the metric vector already paid
            # for (the CLI e2e pins dispatch and device_get counts)
            losses, health_host = fetch_values(losses, health)

        diag.on_health(policy_step_count, health_host)
        aggregator.update("Loss/policy_loss", float(losses[0]))
        aggregator.update("Loss/value_loss", float(losses[1]))
        aggregator.update("Loss/entropy_loss", float(losses[2]))
        aggregator.update("Grads/global_norm", float(losses[3]))
        diag.on_update(
            policy_step_count,
            {
                "Loss/policy_loss": float(losses[0]),
                "Loss/value_loss": float(losses[1]),
                "Loss/entropy_loss": float(losses[2]),
                "Grads/global_norm": float(losses[3]),
            },
            nonfinite=float(losses[4]),
        )

        # ---- logging (reference ppo.py:386-413) ---------------------------
        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if timers.get("Time/train_time", 0) > 0:
                metrics["Time/sps_train"] = (
                    (iter_num * cfg.algo.update_epochs * num_minibatches) / timers["Time/train_time"]
                )
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # ---- checkpoint (reference ppo.py:428-442) ------------------------
        # a pending preemption (signal or drill) forces the branch: the save
        # below IS the emergency snapshot (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            ckpt_state = {
                "agent": jax.tree_util.tree_map(np.asarray, params),
                "opt_state": jax.tree_util.tree_map(np.asarray, opt_state),
                "iter_num": iter_num,
                "policy_step": policy_step_count,
                "last_log": last_log,
                "last_checkpoint": last_checkpoint,
                "batch_size": batch_size * world_size,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{policy_step_count}_0.ckpt")
            with diag.span("checkpoint"):
                runtime.call(
                    "on_checkpoint_coupled",
                    ckpt_path=ckpt_path,
                    state=ckpt_state,
                    replay_buffer=None,
                )
            diag.on_checkpoint(policy_step_count, ckpt_path)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)

    envs.close()
    # ---- final test episode (reference ppo.py:445-453) --------------------
    cumulative_rew = None
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(agent.apply, params, test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)

    if cfg.model_manager.disabled is False and runtime.is_global_zero:  # pragma: no cover
        from sheeprl_tpu.utils.mlflow import log_models

        log_models(cfg, {"agent": params}, log_dir)
    logger.finalize()
    diag.close("completed")
    return cumulative_rew
