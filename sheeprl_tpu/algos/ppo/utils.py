"""PPO helper surface (reference /root/reference/sheeprl/algos/ppo/utils.py)."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import numpy as np

AGGREGATOR_KEYS = {
    "Rewards/rew_avg",
    "Game/ep_len_avg",
    "Loss/value_loss",
    "Loss/policy_loss",
    "Loss/entropy_loss",
    "Grads/global_norm",
}
MODELS_TO_REGISTER = {"agent"}


def host_obs_slab(
    obs: Dict[str, np.ndarray],
    *,
    cnn_keys: Sequence[str] = (),
    mlp_keys: Sequence[str] = (),
    num_envs: int = 1,
) -> Dict[str, np.ndarray]:
    """Batched host-side obs slab (views/casts only — the array layout
    ``prepare_obs`` stages)."""
    out: Dict[str, np.ndarray] = {}
    for k in cnn_keys:
        arr = np.asarray(obs[k])
        out[k] = arr.reshape(num_envs, *arr.shape[-3:])
    for k in mlp_keys:
        out[k] = np.asarray(obs[k], dtype=np.float32).reshape(num_envs, -1)
    return out


def prepare_obs(
    obs: Dict[str, np.ndarray],
    *,
    cnn_keys: Sequence[str] = (),
    mlp_keys: Sequence[str] = (),
    num_envs: int = 1,
    sharding: Any = None,
) -> Dict[str, jax.Array]:
    """Host obs dict → device arrays shaped ``[num_envs, ...]``
    (reference utils.py:17-33). Pixel normalization (/255) happens inside the
    agent so the transfer stays uint8 (4x less host→HBM traffic).  The whole
    slab is staged in ONE ``jax.device_put`` — pass a reused ``sharding``
    (``envs/player.py::obs_sharding``) from the hot loops so the per-step h2d
    count stays 1 regardless of ``num_envs`` and key count."""
    slab = host_obs_slab(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
    return jax.device_put(slab, sharding) if sharding is not None else jax.device_put(slab)


def test(agent_apply, params, env, runtime, cfg, log_dir: str) -> float:
    """Run one greedy episode and log Test/cumulative_reward
    (reference utils.py:36-60)."""
    from sheeprl_tpu.utils.logger import get_logger  # lazy, avoids cycle

    done = False
    cumulative_rew = 0.0
    obs, _ = env.reset(seed=cfg.seed)
    cnn_keys = cfg.algo.cnn_keys.encoder
    mlp_keys = cfg.algo.mlp_keys.encoder
    key = jax.random.PRNGKey(cfg.seed or 0)
    while not done:
        torch_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys)
        actions, _, _, _ = agent_apply(params, torch_obs, key=key, greedy=True)
        actions = np.asarray(actions)
        if env.action_space.__class__.__name__ == "Discrete":
            env_actions = int(actions[0, 0])
        elif env.action_space.__class__.__name__ == "MultiDiscrete":
            env_actions = actions[0].astype(np.int64)
        else:
            env_actions = actions.reshape(env.action_space.shape)
        obs, reward, terminated, truncated, _ = env.step(env_actions)
        done = bool(terminated or truncated)
        cumulative_rew += float(reward)
        if cfg.dry_run:
            done = True
    env.close()
    return cumulative_rew


def normalize_obs(
    obs: Dict[str, np.ndarray], cnn_keys: Sequence[str], obs_keys: Sequence[str]
) -> Dict[str, np.ndarray]:
    return {k: obs[k] for k in obs_keys}
