"""Decoupled PPO — TPU-native re-design of
/root/reference/sheeprl/algos/ppo/ppo_decoupled.py:32-670.

Reference topology: rank-0 player process + ranks 1..N-1 trainer DDP group,
wired by hand-built NCCL/Gloo groups — rollouts scattered with
``scatter_object_list`` (:294-299), updated parameters broadcast back as one
flat vector (:302-305).

TPU single-controller equivalent (SURVEY §2.4): **device 0 is the player,
devices 1..N-1 are the trainer mesh.**  The object scatter becomes a
``device_put`` of the rollout sharded over the trainer sub-mesh (data rides
ICI, not host RPC); the flat-parameter broadcast becomes a ``device_put`` of
the params pytree back onto the player device.  The control flow keeps the
reference's synchronous pipeline: rollout → scatter → train epochs (DDP ≡
``pmean`` on the sub-mesh) → params back to player.
"""

from __future__ import annotations

import os
from typing import Dict

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.algos.ppo.agent import build_agent
from sheeprl_tpu.algos.ppo.ppo import make_train_step
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, MODELS_TO_REGISTER, prepare_obs, test  # noqa: F401
from sheeprl_tpu.data.slab import step_slab
from sheeprl_tpu.envs.player import fetch_values
from sheeprl_tpu.config import instantiate
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.envs.env import make_env, make_env_fns, pipelined_vector_env
from sheeprl_tpu.ops.numerics import gae
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.parallel.precision import cast_floating, compute_dtype_of
from sheeprl_tpu.utils.registry import register_algorithm
from sheeprl_tpu.utils.timer import timer
from sheeprl_tpu.utils.utils import get_diagnostics, polynomial_decay, save_configs


@register_algorithm(decoupled=True)
def main(runtime, cfg):
    world_size = runtime.world_size
    if world_size < 2:
        raise RuntimeError(
            "Decoupled PPO needs at least 2 devices: 1 player + >=1 trainer "
            f"(got fabric.devices={world_size})"
        )
    player_device = runtime.devices[0]
    trainer_devices = runtime.devices[1:]
    trainer_mesh = Mesh(np.asarray(trainer_devices), ("data",))
    n_trainers = len(trainer_devices)

    num_envs = cfg.env.num_envs
    rollout_steps = cfg.algo.rollout_steps
    batch_size = cfg.algo.per_rank_batch_size
    total_local = rollout_steps * num_envs
    if total_local % n_trainers != 0:
        raise ValueError(
            f"rollout_steps*num_envs ({total_local}) must be divisible by the number of trainers ({n_trainers})"
        )
    n_per_trainer = total_local // n_trainers
    if n_per_trainer % batch_size != 0:
        raise ValueError(
            f"Per-trainer rollout ({n_per_trainer}) must be divisible by per_rank_batch_size ({batch_size})"
        )
    num_minibatches = n_per_trainer // batch_size

    rng_key = runtime.seed_everything(cfg.seed)
    logger = get_logger(runtime, cfg)
    log_dir = get_log_dir(runtime, cfg.root_dir, cfg.run_name)
    if runtime.is_global_zero:
        save_configs(cfg, log_dir)
        logger.log_hyperparams(cfg.as_dict() if hasattr(cfg, "as_dict") else dict(cfg))
    diag = get_diagnostics(runtime, cfg, log_dir)
    aggregator: MetricAggregator = instantiate(cfg.metric.aggregator)
    if cfg.metric.log_level == 0:
        aggregator.disabled = True
    timer.disabled = cfg.metric.log_level == 0 or cfg.metric.disable_timer

    envs = pipelined_vector_env(cfg, make_env_fns(cfg, log_dir, "train"))
    observation_space = envs.single_observation_space
    action_space = envs.single_action_space
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = cfg.algo.cnn_keys.encoder
    mlp_keys = cfg.algo.mlp_keys.encoder
    obs_keys = list(cnn_keys) + list(mlp_keys)
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    state = runtime.load(cfg.checkpoint.resume_from) if cfg.checkpoint.resume_from else None
    agent, params, _ = build_agent(
        runtime, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    params = cast_floating(params, runtime.param_dtype)

    policy_steps_per_iter = int(num_envs * rollout_steps)
    total_iters = int(cfg.algo.total_steps // policy_steps_per_iter) if not cfg.dry_run else 1
    if cfg.algo.anneal_lr:
        schedule = optax.linear_schedule(
            init_value=cfg.algo.optimizer.learning_rate,
            end_value=0.0,
            transition_steps=max(1, total_iters * cfg.algo.update_epochs * num_minibatches),
        )
        base_opt = instantiate(cfg.algo.optimizer, learning_rate=schedule)
    else:
        base_opt = instantiate(cfg.algo.optimizer)
    chain = []
    if cfg.algo.max_grad_norm and cfg.algo.max_grad_norm > 0:
        chain.append(optax.clip_by_global_norm(cfg.algo.max_grad_norm))
    chain.append(base_opt)
    optimizer = optax.chain(*chain)
    opt_state = optimizer.init(params)
    if state and "opt_state" in state:
        opt_state = jax.tree_util.tree_map(
            lambda ref, saved: jnp.asarray(saved, dtype=getattr(ref, "dtype", None)),
            opt_state,
            state["opt_state"],
        )

    # trainer-resident replicated params/opt state; player-resident copy
    trainer_repl = NamedSharding(trainer_mesh, P())
    trainer_data_sharding = NamedSharding(trainer_mesh, P("data"))
    trainer_params = jax.device_put(params, trainer_repl)
    opt_state = jax.device_put(opt_state, trainer_repl)
    player_params = jax.device_put(params, player_device)

    train_step = diag.instrument(
        "train_step",
        make_train_step(agent, optimizer, cfg, trainer_mesh, num_minibatches, batch_size),
        kind="train",
        donate_argnums=(0, 1),  # trainer params, opt_state — audited at first dispatch
    )
    diag.register_footprint("params", trainer_params)
    diag.register_footprint("opt_state", opt_state)
    diag.register_footprint("player_params", player_params)

    @jax.jit
    def _policy_step(params, obs, key):
        actions, logprobs, _, values = agent.apply(params, obs, key=key)
        return actions, logprobs, values

    _policy_step = diag.instrument("policy_step", _policy_step, kind="rollout")
    # one staged h2d (straight onto the player device) + one blocking fetch
    # per vector step (see ppo.py); the reused sharding makes prepare_obs's
    # single device_put land on the player chip with no second hop
    stage_sharding = jax.sharding.SingleDeviceSharding(player_device)

    def policy_step(params, obs, key):
        obs = jax.device_put(obs, player_device)
        return _policy_step(params, obs, key)

    @jax.jit
    def value_step(params, obs):
        return agent.apply(params, obs, method="get_values")

    @jax.jit
    def gae_step(params, last_obs, rewards, values, dones):
        next_value = agent.apply(params, last_obs, method="get_values")
        return gae(rewards, values, dones, next_value, rollout_steps, cfg.algo.gamma, cfg.algo.gae_lambda)

    rb = ReplayBuffer(
        cfg.buffer.size,
        num_envs,
        memmap=cfg.buffer.memmap,
        memmap_dir=os.path.join(log_dir, "memmap_buffer"),
        obs_keys=obs_keys,
    )
    diag.track_buffer("replay", rb)

    start_iter = (state["iter_num"] if state else 0) + 1
    policy_step_count = state["policy_step"] if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0

    initial_ent = cfg.algo.ent_coef
    initial_clip = cfg.algo.clip_coef
    ent_coef = initial_ent
    clip_coef = initial_clip

    obs, _ = envs.reset(seed=cfg.seed)

    for iter_num in range(start_iter, total_iters + 1):
        # ---- PLAYER: rollout on device 0 (reference ppo_decoupled.py:169-299)
        with timer("Time/env_interaction_time"), diag.span("rollout", role="player"):
            for _ in range(rollout_steps):
                policy_step_count += num_envs
                diag.note_env_steps(num_envs)
                rng_key, step_key = jax.random.split(rng_key)
                torch_obs = prepare_obs(
                    obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs, sharding=stage_sharding
                )
                actions, logprobs, values = policy_step(player_params, torch_obs, step_key)
                actions_np, logprobs_np, values_np = fetch_values(actions, logprobs, values)
                if is_continuous:
                    env_actions = actions_np.reshape(num_envs, -1)
                elif is_multidiscrete:
                    env_actions = actions_np.astype(np.int64)
                else:
                    env_actions = actions_np[:, 0].astype(np.int64)

                # split-phase: env workers step while the player copies its
                # outputs + current obs into the step record (see ppo.py)
                with diag.span("env_step_async"):
                    envs.step_async(env_actions)
                step_data: Dict[str, np.ndarray] = step_slab(
                    num_envs,
                    {
                        **{k: obs[k] for k in obs_keys},
                        "actions": actions_np,
                        "logprobs": logprobs_np,
                        "values": values_np,
                    },
                )
                with diag.span("env_wait"):
                    next_obs, rewards, terminated, truncated, info = envs.step_wait()
                dones = np.logical_or(terminated, truncated).reshape(num_envs, 1).astype(np.float32)
                rewards = np.asarray(rewards, dtype=np.float32).reshape(num_envs, 1)
                if cfg.env.clip_rewards:
                    rewards = np.tanh(rewards)
                if "final_obs" in info and np.any(truncated):
                    final_obs = info["final_obs"]
                    trunc_idx = np.nonzero(truncated)[0]
                    stacked = {k: np.stack([np.asarray(final_obs[i][k]) for i in trunc_idx]) for k in obs_keys}
                    t_obs = prepare_obs(stacked, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=len(trunc_idx))
                    vals = np.asarray(value_step(player_params, jax.device_put(t_obs, player_device)))
                    rewards[trunc_idx] += cfg.algo.gamma * vals.reshape(-1, 1)

                step_data.update(step_slab(num_envs, {"rewards": rewards, "dones": dones}))
                rb.add(step_data, validate_args=cfg.buffer.validate_args)

                if "final_info" in info and "episode" in info["final_info"]:
                    ep = info["final_info"]["episode"]
                    mask = ep.get("_r", info["final_info"].get("_episode"))
                    if mask is not None and np.any(mask):
                        for r, l in zip(ep["r"][mask], ep["l"][mask]):
                            aggregator.update("Rewards/rew_avg", float(r))
                            aggregator.update("Game/ep_len_avg", float(l))
                obs = next_obs

        local = {k: np.asarray(rb[k][:rollout_steps]) for k in rb.buffer.keys()}
        torch_last_obs = prepare_obs(obs, cnn_keys=cnn_keys, mlp_keys=mlp_keys, num_envs=num_envs)
        returns, advantages = gae_step(
            player_params,
            jax.device_put(torch_last_obs, player_device),
            jnp.asarray(local["rewards"]),
            jnp.asarray(local["values"]),
            jnp.asarray(local["dones"]),
        )
        local["returns"] = np.asarray(returns)
        local["advantages"] = np.asarray(advantages)

        # ---- "scatter" to trainers: shard over the trainer sub-mesh --------
        flat = {
            "obs": {k: local[k].reshape(total_local, *local[k].shape[2:]) for k in obs_keys},
            "actions": local["actions"].reshape(total_local, -1),
            "logprobs": local["logprobs"].reshape(total_local, -1),
            "values": local["values"].reshape(total_local, -1),
            "returns": local["returns"].reshape(total_local, -1),
            "advantages": local["advantages"].reshape(total_local, -1),
        }
        device_data = jax.tree_util.tree_map(
            lambda x: jax.device_put(jnp.asarray(x), trainer_data_sharding), flat
        )
        device_data = diag.maybe_inject_nan(iter_num, device_data)
        device_data = diag.maybe_inject_shape_change(iter_num, device_data, pad=n_trainers)

        if cfg.algo.anneal_clip_coef:
            clip_coef = polynomial_decay(
                iter_num, initial=initial_clip, final=0.0, max_decay_steps=total_iters, power=1.0
            )
        if cfg.algo.anneal_ent_coef:
            ent_coef = polynomial_decay(
                iter_num, initial=initial_ent, final=0.0, max_decay_steps=total_iters, power=1.0
            )

        # ---- TRAINERS: update epochs on the sub-mesh ----------------------
        # quarantined: a chaos-injected (or real) dispatch failure rolls the
        # trainer back to the last-good snapshot instead of killing the run
        # (bounded by resilience.isolation.retry_budget; howto/resilience.md)
        trained_ok = True
        try:
            with timer("Time/train_time"), diag.span("train", role="trainer"):
                diag.maybe_chaos_trainer_fault(iter_num)
                rng_key, train_key = jax.random.split(rng_key)
                coefs = (
                    jnp.asarray(clip_coef, jnp.float32),
                    jnp.asarray(ent_coef, jnp.float32),
                    jnp.asarray(cfg.algo.vf_coef, jnp.float32),
                )
                trainer_params, opt_state, losses, health = train_step(
                    trainer_params, opt_state, device_data, train_key, coefs
                )
                # one blocking d2h for metrics + health stats together
                losses, health_host = fetch_values(losses, health)
        except Exception as err:
            restored = diag.quarantine(err, iter_num, policy_step_count)
            if restored is None:
                raise
            # the dispatch may have consumed (donated) the live buffers; the
            # restore re-materializes both trees from the host snapshot.  No
            # metrics exist for this iteration, but the loop still falls
            # through to the preemption/checkpoint boundary below.
            trainer_params = jax.device_put(restored["params"], trainer_repl)
            opt_state = jax.device_put(restored["opt_state"], trainer_repl)
            trained_ok = False

        if trained_ok:
            # ---- last-good fencing: the params hop to the player only
            # happens when the update judges healthy (in-graph nonfinite
            # count + fetched health norms + open anomalies — no extra device
            # syncs); a rejected update leaves the player acting on its
            # last-good params
            if diag.gate_promotion(
                iter_num, policy_step_count, stats=health_host, nonfinite=float(losses[4])
            ):
                # ---- params broadcast back to the player (reference :302-305)
                player_params = jax.device_put(trainer_params, player_device)
                diag.refresh_last_good(iter_num, trainer_params, opt_state)

            diag.on_health(policy_step_count, health_host)
            aggregator.update("Loss/policy_loss", float(losses[0]))
            aggregator.update("Loss/value_loss", float(losses[1]))
            aggregator.update("Loss/entropy_loss", float(losses[2]))
            aggregator.update("Grads/global_norm", float(losses[3]))
            try:
                diag.on_update(
                    policy_step_count,
                    {
                        "Loss/policy_loss": float(losses[0]),
                        "Loss/value_loss": float(losses[1]),
                        "Loss/entropy_loss": float(losses[2]),
                        "Grads/global_norm": float(losses[3]),
                    },
                    nonfinite=float(losses[4]),
                )
            except Exception as err:
                # sentinel policy=halt on a fenced update: roll the trainer
                # back to the last-good snapshot and keep the run alive (the
                # player never saw the bad params — the gate already held
                # them)
                restored = diag.quarantine(err, iter_num, policy_step_count)
                if restored is None:
                    raise
                trainer_params = jax.device_put(restored["params"], trainer_repl)
                opt_state = jax.device_put(restored["opt_state"], trainer_repl)

        if policy_step_count - last_log >= cfg.metric.log_every or iter_num == total_iters or cfg.dry_run:
            metrics = aggregator.compute()
            timers = timer.compute()
            if timers.get("Time/env_interaction_time", 0) > 0:
                metrics["Time/sps_env_interaction"] = (
                    (policy_step_count - last_log) / timers["Time/env_interaction_time"]
                )
            if timers.get("Time/train_time", 0) > 0:
                metrics["Time/sps_train"] = (
                    (iter_num * cfg.algo.update_epochs * num_minibatches) / timers["Time/train_time"]
                )
            if runtime.is_global_zero:
                logger.log_metrics(metrics, policy_step_count)
            aggregator.reset()
            timer.reset()
            last_log = policy_step_count

        # a pending preemption (signal or drill) or an exhausted staleness
        # budget forces the branch: the save below IS the emergency snapshot
        # (howto/resilience.md)
        preempt_now = diag.preempt_due(iter_num)
        fence_halt_now = diag.fence_halt_due()
        if (
            (cfg.checkpoint.every > 0 and policy_step_count - last_checkpoint >= cfg.checkpoint.every)
            or cfg.dry_run
            or preempt_now
            or fence_halt_now
            or (iter_num == total_iters and cfg.checkpoint.save_last)
        ):
            last_checkpoint = policy_step_count
            agent_save = jax.tree_util.tree_map(np.asarray, trainer_params)
            opt_save = jax.tree_util.tree_map(np.asarray, opt_state)
            ckpt_iter, ckpt_step = iter_num, policy_step_count
            if fence_halt_now:
                # the fence escalated BECAUSE the live trainer state is bad
                # (under policy=warn the NaN update was applied): the
                # emergency snapshot must be the last-good state, not the
                # corruption it is escaping — with the counters (and hence
                # the file/manifest step) of the iteration it came FROM, so
                # a resume never claims progress that never happened
                last_good = diag.last_good_state()
                if last_good is not None:
                    agent_save, opt_save = last_good["params"], last_good["opt_state"]
                    ckpt_iter = last_good["iter_num"]
                    ckpt_step = ckpt_iter * policy_steps_per_iter
            ckpt_state = {
                "agent": agent_save,
                "opt_state": opt_save,
                "iter_num": ckpt_iter,
                "policy_step": ckpt_step,
                "last_log": min(last_log, ckpt_step),
                "last_checkpoint": min(last_checkpoint, ckpt_step),
                "batch_size": batch_size * n_trainers,
            }
            ckpt_path = os.path.join(log_dir, "checkpoint", f"ckpt_{ckpt_step}_0.ckpt")
            with diag.span("checkpoint"):
                runtime.call("on_checkpoint_player", ckpt_path=ckpt_path, state=ckpt_state, replay_buffer=None)
            diag.on_checkpoint(policy_step_count, ckpt_path)
            if preempt_now:
                envs.close()
                diag.on_preempted(policy_step_count, iter_num, ckpt_path)
            if fence_halt_now:
                envs.close()
                diag.on_fence_halt(policy_step_count, iter_num, ckpt_path)

    envs.close()
    if runtime.is_global_zero and cfg.algo.run_test:
        test_env = make_env(cfg, cfg.seed, 0, log_dir, "test", vector_env_idx=0)()
        cumulative_rew = test(agent.apply, player_params, test_env, runtime, cfg, log_dir)
        logger.log_metrics({"Test/cumulative_reward": cumulative_rew}, policy_step_count)
    logger.finalize()
    diag.close("completed")
