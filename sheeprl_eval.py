#!/usr/bin/env python3
"""Repo-root shim for evaluation (reference /root/reference/sheeprl_eval.py)."""

from sheeprl_tpu.cli import evaluation

if __name__ == "__main__":
    evaluation()
