"""Benchmark harness: prints ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Mirrors the reference's wall-clock benchmark (reference
/root/reference/benchmarks/benchmark.py + configs/exp/ppo_benchmarks.yaml):
PPO on CartPole-v1, 65536 env steps, logging/test/checkpoint disabled.
Baseline: SheepRL v0.5.5 on 4 CPUs = 81.27 s (BASELINE.md §B), i.e.
~806 env-steps/s. ``vs_baseline`` is the throughput ratio (ours / reference,
higher is better).
"""

from __future__ import annotations

import json
import sys
import time

PPO_BASELINE_SECONDS = 81.27  # reference 1-device wall clock (BASELINE.md §B)
TOTAL_STEPS = 65536


def main() -> None:
    from sheeprl_tpu.cli import run

    args = [
        "exp=ppo_benchmarks",
        "env.capture_video=False",
        "checkpoint.save_last=False",
    ]
    tic = time.perf_counter()
    run(args)
    elapsed = time.perf_counter() - tic
    sps = TOTAL_STEPS / elapsed
    baseline_sps = TOTAL_STEPS / PPO_BASELINE_SECONDS
    print(
        json.dumps(
            {
                "metric": "ppo_cartpole_env_steps_per_sec",
                "value": round(sps, 2),
                "unit": "env-steps/s",
                "vs_baseline": round(sps / baseline_sps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
