"""Benchmark harness: prints ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

Measures the flagship compute path: DreamerV3-S gradient steps/sec on one
chip, batch 16 x sequence 64 on 64x64x3 pixels — the Atari-100K training
configuration (reference configs/exp/dreamer_v3_100k_ms_pacman.yaml; SURVEY
§6 / BASELINE.md §C name env-steps/sec/chip for DreamerV3 as the north-star
metric, and with replay_ratio=1 one gradient step IS one policy step).

Baseline: the reference trains Atari-100K (MsPacman, DV3-S, replay_ratio 1,
action_repeat 4 → ~25_000 gradient steps) in 14 h on one RTX-3080
(reference README.md:46-53) → 25_000 / 50_400 s ≈ 0.496 gradient-steps/s
end-to-end.  ``vs_baseline`` = ours / 0.496 (higher is better).
"""

from __future__ import annotations

import json
import time

BASELINE_GRAD_STEPS_PER_SEC = 25_000 / (14 * 3600)
WARMUP_STEPS = 3
MEASURE_STEPS = 20


def main() -> None:
    import gymnasium as gym
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments_state
    from sheeprl_tpu.config import compose, instantiate

    cfg = compose(
        [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo=dreamer_v3_S",
            "algo.per_rank_batch_size=16",
            "algo.per_rank_sequence_length=64",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
            "env.capture_video=False",
            "metric.log_level=0",
        ]
    )
    obs_space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8)})
    actions_dim = (6,)  # MsPacman action space size
    world_model_def, actor_def, critic_def, params = build_agent(
        None, actions_dim, False, cfg, obs_space
    )
    optimizers = {
        "world_model": optax.chain(
            optax.clip_by_global_norm(cfg.algo.world_model.clip_gradients),
            instantiate(cfg.algo.world_model.optimizer),
        ),
        "actor": optax.chain(
            optax.clip_by_global_norm(cfg.algo.actor.clip_gradients),
            instantiate(cfg.algo.actor.optimizer),
        ),
        "critic": optax.chain(
            optax.clip_by_global_norm(cfg.algo.critic.clip_gradients),
            instantiate(cfg.algo.critic.optimizer),
        ),
    }
    opt_states = {
        "world_model": optimizers["world_model"].init(params["world_model"]),
        "actor": optimizers["actor"].init(params["actor"]),
        "critic": optimizers["critic"].init(params["critic"]),
    }
    moments_state = init_moments_state()
    train_step = make_train_step(world_model_def, actor_def, critic_def, optimizers, cfg, actions_dim, False)

    T, B = cfg.algo.per_rank_sequence_length, cfg.algo.per_rank_batch_size
    rng = np.random.default_rng(0)
    batch = {
        "rgb": jnp.asarray(rng.integers(0, 255, (T, B, 3, 64, 64)), jnp.float32) / 255.0 - 0.5,
        "actions": jnp.asarray(rng.integers(0, 2, (T, B, actions_dim[0])), jnp.float32),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)), jnp.float32),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    key = jax.random.PRNGKey(0)
    tau = jnp.float32(0.02)

    for _ in range(WARMUP_STEPS):
        key, sub = jax.random.split(key)
        params, opt_states, moments_state, metrics = train_step(
            params, opt_states, moments_state, batch, sub, tau
        )
    jax.block_until_ready(metrics)

    tic = time.perf_counter()
    for _ in range(MEASURE_STEPS):
        key, sub = jax.random.split(key)
        params, opt_states, moments_state, metrics = train_step(
            params, opt_states, moments_state, batch, sub, tau
        )
    jax.block_until_ready(metrics)
    elapsed = time.perf_counter() - tic
    steps_per_sec = MEASURE_STEPS / elapsed

    print(
        json.dumps(
            {
                "metric": "dreamer_v3_S_grad_steps_per_sec",
                "value": round(steps_per_sec, 3),
                "unit": "grad-steps/s (batch 16 x seq 64, 64x64x3)",
                "vs_baseline": round(steps_per_sec / BASELINE_GRAD_STEPS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
