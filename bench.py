"""Benchmark harness: prints ONE JSON line
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}``.

Flagship: DreamerV3-S on 64x64x3 pixels, batch 16 x sequence 64 — the
Atari-100K training configuration (reference
configs/exp/dreamer_v3_100k_ms_pacman.yaml; BASELINE.md §C names
end-to-end steps/sec/chip as the DreamerV3 north-star metric).

Three honest measurements (VERDICT r1 item 3):

1. **compute grad-steps/s** — per-step wall time with a per-step
   ``block_until_ready`` (no async-dispatch pipelining flattery), median of
   ``MEASURE_STEPS``.
2. **MFU** — XLA ``cost_analysis()`` FLOPs of the compiled train step vs the
   chip's peak for the precision in use.
3. **end-to-end grad-steps/s** — the real loop: player inference + env step +
   replay add/sample + host->device staging + train step, replay_ratio 1 on a
   dummy pixel env.  This is like-for-like with the reference baseline.

Baseline: the reference trains Atari-100K (MsPacman, DV3-S, replay_ratio 1,
action_repeat 4 -> 25_000 gradient steps == policy steps) in 14 h on one
RTX-3080 *end-to-end* (reference README.md:46-53) -> 25_000 / 50_400 s
= 0.496 grad-steps/s.  ``vs_baseline`` compares our END-TO-END number
against it; the compute-only number is reported separately.

Precision defaults to bf16-mixed (TPU-native); override with
``BENCH_PRECISION=32-true|bf16-mixed|bf16-true``.
"""

from __future__ import annotations

import json
import os
import time

BASELINE_E2E_GRAD_STEPS_PER_SEC = 25_000 / (14 * 3600)
# Pinned CPU floor for the fallback liveness workload (VERDICT item 5): the
# DV3-XS vector probe measured 3.43 grad-steps/s uncontended and 1.57-1.63/s
# under driver-side CPU contention (~2x variance), so the floor is pinned at
# the conservative (contended) end.  `vs_cpu_baseline` >= 1.0 is healthy;
# only sustained drops WELL below 1.0 are regressions — see the caveat field
# emitted next to it.
CPU_FALLBACK_FLOOR_GRAD_STEPS_PER_SEC = 1.5
CPU_FALLBACK_FLOOR_CAVEAT = (
    "conservative floor pinned from the contended r04-r07 runs (1.57-1.63/s; "
    "uncontended probe 3.43/s): CPU contention adds ~2x variance, so treat "
    "vs_cpu_baseline as a regression signal only when it drops well below "
    "1.0 across consecutive rounds, not as a performance number"
)
WARMUP_STEPS = 3
# large enough that the single value-fetch barrier's tunnel round trip
# amortizes to noise (see measure_compute's timing discipline note)
MEASURE_STEPS = 150
E2E_WARMUP_ITERS = 8
E2E_MEASURE_ITERS = 200

# peak dense-matmul FLOP/s per chip by device kind (MXU).  The v5-lite/v5e
# MXU peaks: 197 TFLOP/s bf16, ~98.5 TFLOP/s fp32 (fp32 runs at half rate
# through the same systolic array).  Unknown kinds fall back to these.
_PEAKS = {
    "default": {"bf16": 197e12, "f32": 98.5e12},
    "v4": {"bf16": 275e12, "f32": 137.5e12},
    "v5p": {"bf16": 459e12, "f32": 229.5e12},
}


def _chip_peak(device_kind: str, precision: str):
    """(peak FLOP/s, assumed) — ``assumed`` is True when the device kind is
    not recognized and the v5e peak is used as a stand-in (the reported MFU
    is then marked, not silently wrong — ADVICE r2)."""
    kind = device_kind.lower()
    if "v4" in kind:
        peaks = _PEAKS["v4"]
    elif "v5p" in kind:
        peaks = _PEAKS["v5p"]
    else:
        # v5e/v5-lite get the default table as their own entry; anything else
        # falls back to it with the MFU explicitly marked as estimated
        peaks = _PEAKS["default"]
        if not any(t in kind for t in ("v5 lite", "v5e", "v5lite")):
            return peaks["bf16"] if "bf16" in precision or "16" in precision else peaks["f32"], True
    return peaks["bf16"] if "bf16" in precision or "16" in precision else peaks["f32"], False


def _build(cfg_overrides, actions_dim=(6,), mesh=None):
    import gymnasium as gym
    import numpy as np
    import optax

    from sheeprl_tpu.algos.dreamer_v3.agent import build_agent
    from sheeprl_tpu.algos.dreamer_v3.dreamer_v3 import make_train_step
    from sheeprl_tpu.algos.dreamer_v3.utils import init_moments_state
    from sheeprl_tpu.config import compose, instantiate
    from sheeprl_tpu.parallel.precision import cast_floating, resolve_precision

    cfg = compose(cfg_overrides)
    obs_space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (3, 64, 64), np.uint8),
            "state": gym.spaces.Box(-np.inf, np.inf, (10,), np.float32),
        }
    )
    world_model_def, actor_def, critic_def, params = build_agent(
        None, actions_dim, False, cfg, obs_space
    )
    params = cast_floating(params, resolve_precision(cfg.fabric.precision)[0])
    optimizers = {
        k: optax.chain(
            optax.clip_by_global_norm(getattr(cfg.algo, k).clip_gradients),
            instantiate(getattr(cfg.algo, k).optimizer),
        )
        for k in ("world_model", "actor", "critic")
    }
    opt_states = {k: optimizers[k].init(params[k]) for k in optimizers}
    moments_state = init_moments_state()
    train_step = make_train_step(
        world_model_def, actor_def, critic_def, optimizers, cfg, actions_dim, False, mesh=mesh
    )
    return cfg, world_model_def, actor_def, critic_def, params, opt_states, moments_state, train_step


def build_train_step_and_batch(
    precision: str,
    size: str = "S",
    batch_size: int = 16,
    sequence_length: int = 64,
    extra_overrides=(),
    mesh=None,
):
    """One compiled-workload recipe, shared by ``measure_compute`` and
    ``tools/perf_study.py``'s lever study so the two can never drift: the
    flagship DV3 pixel config + a synthetic batch derived from the composed
    config's obs keys.  ``mesh`` builds the distributed step (DP shard_map or
    FSDP global-view jit — state/batch placement is the caller's job).
    Returns ``(cfg, train_step, state, batch)`` with ``state = {params,
    opt_states, moments_state}``."""
    import jax.numpy as jnp
    import numpy as np

    cfg, _, _, _, params, opt_states, moments_state, train_step = _build(
        [
            "exp=dreamer_v3",
            "env=dummy",
            "env.id=discrete_dummy",
            f"algo=dreamer_v3_{size}",
            f"algo.per_rank_batch_size={batch_size}",
            f"algo.per_rank_sequence_length={sequence_length}",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.cnn_keys.decoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "algo.mlp_keys.decoder=[]",
            "env.capture_video=False",
            "metric.log_level=0",
            f"fabric.precision={precision}",
            *extra_overrides,
        ],
        mesh=mesh,
    )
    T, B = cfg.algo.per_rank_sequence_length, cfg.algo.per_rank_batch_size
    rng = np.random.default_rng(0)
    batch = {
        "actions": jnp.asarray(rng.integers(0, 2, (T, B, 6)), jnp.float32),
        "rewards": jnp.asarray(rng.normal(size=(T, B, 1)), jnp.float32),
        "terminated": jnp.zeros((T, B, 1), jnp.float32),
        "is_first": jnp.zeros((T, B, 1), jnp.float32),
    }
    for k in set(cfg.algo.cnn_keys.encoder) | set(cfg.algo.cnn_keys.decoder):
        batch[k] = jnp.asarray(rng.integers(0, 255, (T, B, 3, 64, 64)), jnp.float32) / 255.0 - 0.5
    for k in set(cfg.algo.mlp_keys.encoder) | set(cfg.algo.mlp_keys.decoder):
        batch[k] = jnp.asarray(rng.normal(size=(T, B, 10)), jnp.float32)
    from sheeprl_tpu.algos.dreamer_v3.utils import rssm_scan_spec

    if rssm_scan_spec(cfg)[0] > 1:
        # chunked-scan variants consume replay-stored RSSM states; synthetic
        # stand-ins keep the compiled graph and its shapes honest (values
        # only matter for convergence, not for the perf measurement)
        recurrent_size = cfg.algo.world_model.recurrent_model.recurrent_state_size
        stoch_flat = cfg.algo.world_model.stochastic_size * cfg.algo.world_model.discrete_size
        batch["rssm_recurrent"] = jnp.asarray(
            rng.normal(size=(T, B, recurrent_size)) * 0.01, jnp.float32
        )
        batch["rssm_posterior"] = jnp.zeros((T, B, stoch_flat), jnp.float32)
        batch["rssm_valid"] = jnp.ones((T, B, 1), jnp.float32)
    state = {"params": params, "opt_states": opt_states, "moments_state": moments_state}
    return cfg, train_step, state, batch


def measure_compute(
    precision: str,
    size: str = "S",
    batch_size: int = 16,
    measure_steps: int = MEASURE_STEPS,
    extra_overrides=(),
):
    """Per-step timed gradient steps + MFU on random device-resident data.
    ``extra_overrides`` lets the perf study isolate phases (horizon=1, short
    sequences, vector-only observations)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    cfg, train_step, state, batch = build_train_step_and_batch(
        precision, size=size, batch_size=batch_size, extra_overrides=extra_overrides
    )
    params, opt_states, moments_state = state["params"], state["opt_states"], state["moments_state"]
    key = jax.random.PRNGKey(0)
    tau = jnp.float32(0.02)

    # FLOPs of one compiled step (XLA cost analysis)
    flops = None
    try:
        compiled = train_step.lower(params, opt_states, moments_state, batch, key, tau).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0)) or None
    except Exception:
        pass

    for _ in range(WARMUP_STEPS):
        key, sub = jax.random.split(key)
        params, opt_states, moments_state, metrics = train_step(
            params, opt_states, moments_state, batch, sub, tau
        )[:4]
    _ = np.asarray(metrics)  # warmup barrier: fetch real values

    # Timing discipline (VERDICT r1: a dispatch-only measurement implied
    # >chip-peak FLOP/s): through the axon tunnel even block_until_ready can
    # report early, so the only trustworthy barrier is fetching VALUES that
    # depend on the work.  Each step's params feed the next, so fetching the
    # final metrics forces the entire N-step chain; amortized time per step
    # carries one tunnel round trip across all N steps.
    t0 = time.perf_counter()
    for _ in range(measure_steps):
        key, sub = jax.random.split(key)
        params, opt_states, moments_state, metrics = train_step(
            params, opt_states, moments_state, batch, sub, tau
        )[:4]
    final_metrics = np.asarray(metrics)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(final_metrics).all()
    step_s = elapsed / measure_steps
    device_kind = jax.devices()[0].device_kind
    peak, peak_assumed = _chip_peak(device_kind, precision)
    tflops = (flops / step_s / 1e12) if flops else None
    mfu = (flops / step_s) / peak if flops else None
    out = {
        "grad_steps_per_sec_compute": round(1.0 / step_s, 3),
        "step_ms": round(step_s * 1e3, 2),
        "flops_per_step": flops,
        "tflops_per_sec": round(tflops, 2) if tflops else None,
        "mfu": round(mfu, 4) if mfu else None,
        # same field names as the live telemetry layer journals
        # (sheeprl_tpu/diagnostics/telemetry.py), so offline bench numbers
        # and a live run's journal rows diff directly (ISSUE 3)
        "Telemetry/tflops_per_sec": round(tflops, 4) if tflops else None,
        "Telemetry/mfu": round(mfu, 4) if mfu else None,
        "device_kind": device_kind,
    }
    if peak_assumed:
        out["peak_assumed"] = "unrecognized device kind — MFU uses the v5e peak as a stand-in"
    if tflops and tflops * 1e12 > peak:
        out["timing_suspect"] = (
            "implied FLOP/s exceeds chip peak — treat compute timing as unreliable"
        )
    return out


#: The PERF.md §4 MFU levers as config-override variants; `mfu_levers`
#: sweeps them against the base graph.  rssm_chunks folds the chunk axis
#: into the batch axis (GRU GEMM at B*K rows), scan_unroll amortizes scan
#: overhead, rssm_pallas routes the recurrent cell through the fused
#: LayerNorm-GRU Pallas kernel (XL shapes are where XLA fusion may lose).
MFU_LEVER_VARIANTS = {
    "base": [],
    "rssm_chunks2": ["algo.rssm_chunks=2"],
    "rssm_chunks4": ["algo.rssm_chunks=4"],
    "unroll8": ["algo.scan_unroll=8"],
    "pallas": ["algo.rssm_pallas=True"],
}


def measure_mfu_levers(
    precision: str,
    size: str = "S",
    batch_size: int = 16,
    sequence_length: int = 64,
    warmup_steps: int = 2,
    measure_steps: int = 8,
    variants=None,
):
    """The scan-lever close-out sweep (ROADMAP item 2): step time of the DV3
    train step under each MFU lever vs the base graph, one variant at a time
    (build → warm → time → free, so HBM holds ONE variant's state — unlike
    the interleaved perf_study harness this is a coarse menu stage; for
    drift-proof A/Bs on a congested tunnel use
    ``tools/perf_study.py --unroll-ab``).

    Reports ``step_ms`` per variant and the speedup vs base.  Deliberately
    NOT MFU per variant: ``cost_analysis()`` FLOPs inflate under unrolled
    scans (PERF.md §4), so step time on the identical batch is the only
    honest cross-variant number — the note field says so in the JSON.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    variants = dict(MFU_LEVER_VARIANTS) if variants is None else dict(variants)
    out = {
        "size": size,
        "batch_size": batch_size,
        "sequence_length": sequence_length,
        "measure_steps": measure_steps,
        "note": (
            "step_ms on the identical batch is the cross-variant metric; "
            "cost_analysis FLOPs (and therefore MFU) inflate under unrolled "
            "scans, and chunked variants change the stored-state batch keys"
        ),
        "points": {},
    }
    base_step_s = None
    for name, extra in variants.items():
        try:
            cfg, train_step, state, batch = build_train_step_and_batch(
                precision,
                size=size,
                batch_size=batch_size,
                sequence_length=sequence_length,
                extra_overrides=list(extra),
            )
            params, opt_states, moments_state = (
                state["params"],
                state["opt_states"],
                state["moments_state"],
            )
            key = jax.random.PRNGKey(0)
            tau = jnp.float32(0.02)
            for _ in range(warmup_steps):
                key, sub = jax.random.split(key)
                params, opt_states, moments_state, metrics = train_step(
                    params, opt_states, moments_state, batch, sub, tau
                )[:4]
            np.asarray(metrics)  # compile + warmup barrier
            t0 = time.perf_counter()
            for _ in range(measure_steps):
                key, sub = jax.random.split(key)
                params, opt_states, moments_state, metrics = train_step(
                    params, opt_states, moments_state, batch, sub, tau
                )[:4]
            final = np.asarray(metrics)  # value barrier forces the chain
            step_s = (time.perf_counter() - t0) / measure_steps
            point = {"step_ms": round(step_s * 1e3, 2), "finite": bool(np.isfinite(final).all())}
            if name == "base":
                base_step_s = step_s
            elif base_step_s:
                point["vs_base"] = round(base_step_s / step_s, 4)
            out["points"][name] = point
        except Exception as err:  # noqa: BLE001 — one variant must not kill the sweep
            out["points"][name] = {"error": repr(err)[:200]}
        finally:
            # drop this variant's params/opt state/batch references before
            # the next build — at XL shapes two variants do not co-reside in
            # HBM (rebinding to None releases the arrays to the allocator)
            params = opt_states = moments_state = batch = state = metrics = None
    return out


def measure_e2e(
    precision: str,
    num_envs: int = 1,
    size: str = "S",
    batch_size: int = 16,
    sequence_length: int = 64,
    pixels: bool = True,
    warmup_iters: int = E2E_WARMUP_ITERS,
    measure_iters: int = E2E_MEASURE_ITERS,
):
    """End-to-end DV3 loop on a dummy env: player inference + env
    step + replay add/sample + one gradient step per policy step
    (replay_ratio 1) — BASELINE.md §C's metric, like the reference's 14 h
    Atari-100K wall clock.  Uses the HBM-resident replay buffer (the
    framework's intended TPU path): per-step host->device traffic is one
    frame, and training batches are gathered inside HBM.

    The defaults are the flagship DV3-S pixel configuration; the CPU
    fallback path shrinks the workload (``size``/``batch_size``/
    ``sequence_length``/``pixels``/iteration counts) so the harness still
    finishes inside the driver budget on a dead tunnel (VERDICT r4 weak #1).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.algos.dreamer_v3.agent import PlayerDV3
    from sheeprl_tpu.algos.dreamer_v3.utils import prepare_obs
    from sheeprl_tpu.data.device_buffer import DeviceSequentialReplayBuffer
    from sheeprl_tpu.envs.env import make_env, vectorized_env

    from sheeprl_tpu.config import compose

    cnn = "[rgb]" if pixels else "[]"
    mlp = "[]" if pixels else "[state]"
    overrides = [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=discrete_dummy",
        f"algo=dreamer_v3_{size}",
        f"algo.per_rank_batch_size={batch_size}",
        f"algo.per_rank_sequence_length={sequence_length}",
        f"algo.cnn_keys.encoder={cnn}",
        f"algo.cnn_keys.decoder={cnn}",
        f"algo.mlp_keys.encoder={mlp}",
        f"algo.mlp_keys.decoder={mlp}",
        f"env.num_envs={num_envs}",
        "env.capture_video=False",
        "metric.log_level=0",
        f"fabric.precision={precision}",
    ]
    env_cfg = compose(overrides)
    envs = vectorized_env(
        [make_env(env_cfg, 42 + i, 0, None, "bench", vector_env_idx=i) for i in range(num_envs)],
        sync=True,
    )
    actions_dim = (envs.single_action_space.n,)
    cfg, wm_def, actor_def, _, params, opt_states, moments_state, train_step = _build(
        overrides, actions_dim=actions_dim
    )
    obs_keys = ["rgb"] if pixels else ["state"]
    cnn_obs_keys = obs_keys if pixels else []
    mlp_obs_keys = [] if pixels else obs_keys
    rb = DeviceSequentialReplayBuffer(4096, n_envs=num_envs, obs_keys=tuple(obs_keys))
    player = PlayerDV3(wm_def, actor_def, actions_dim, num_envs)
    player.init_states(params["world_model"])
    key = jax.random.PRNGKey(0)
    T, B = cfg.algo.per_rank_sequence_length, cfg.algo.per_rank_batch_size

    obs = envs.reset(seed=42)[0]
    step_data = {k: np.asarray(obs[k])[np.newaxis] for k in obs_keys}
    step_data["rewards"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["truncated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["terminated"] = np.zeros((1, num_envs, 1), np.float32)
    step_data["is_first"] = np.ones((1, num_envs, 1), np.float32)

    # prefill so sequence sampling is valid
    for _ in range(T + 8):
        actions = np.asarray(envs.action_space.sample())
        onehot = np.eye(actions_dim[0], dtype=np.float32)[actions].reshape(1, num_envs, -1)
        step_data["actions"] = onehot
        rb.add(step_data)
        obs, rewards, term, trunc, _ = envs.step(actions.reshape(envs.action_space.shape))
        for k in obs_keys:
            step_data[k] = np.asarray(obs[k])[np.newaxis]
        step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
        step_data["terminated"] = np.asarray(term, np.float32).reshape(1, num_envs, 1)
        step_data["truncated"] = np.asarray(trunc, np.float32).reshape(1, num_envs, 1)
        step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)

    from sheeprl_tpu.parallel.dp import normalize_staged

    # the SAME phase accounting the live telemetry layer runs (nesting-aware
    # self-time per span), so the bench's phase breakdown and a live run's
    # Telemetry/phase_pct/* rows are directly comparable
    from sheeprl_tpu.diagnostics.telemetry import Telemetry

    tele = Telemetry({})
    tele.open()

    def one_iter(params, opt_states, moments_state, step_data, obs, key, pipelined):
        """One policy step + one gradient step (ratio 1).

        ``pipelined=True`` replicates the shipped hot loop's dispatch order
        (sheeprl_tpu/algos/dreamer_v3/dreamer_v3.py:600-681): the player
        forward is dispatched, its DEVICE-RESIDENT action array is written
        into the HBM replay ring, the gradient step is dispatched, and only
        then is the action value fetched for ``envs.step`` — the fetch's
        tunnel round trip and host env stepping overlap device compute.
        ``pipelined=False`` is the reference-style serialized order (fetch
        action -> env.step -> train) for an apples-to-apples overlap number.
        """
        key, k_step, k_train = jax.random.split(key, 3)
        with tele.span("rollout"):
            torch_obs = prepare_obs(obs, cnn_keys=cnn_obs_keys, mlp_keys=mlp_obs_keys, num_envs=num_envs)
            actions_jnp = player.get_actions(params["world_model"], params["actor"], torch_obs, k_step)

        def fetch_and_step_envs(step_data, obs):
            actions = np.asarray(actions_jnp)
            real_actions = np.argmax(actions, axis=-1)
            obs, rewards, term, trunc, _ = envs.step(real_actions.reshape(envs.action_space.shape))
            for k in obs_keys:
                step_data[k] = np.asarray(obs[k])[np.newaxis]
            step_data["rewards"] = np.asarray(rewards, np.float32).reshape(1, num_envs, 1)
            step_data["terminated"] = np.asarray(term, np.float32).reshape(1, num_envs, 1)
            step_data["truncated"] = np.asarray(trunc, np.float32).reshape(1, num_envs, 1)
            step_data["is_first"] = np.zeros((1, num_envs, 1), np.float32)
            return step_data, obs

        if pipelined:
            with tele.span("rollout"):
                step_data["actions"] = jnp.reshape(actions_jnp, (1, num_envs, -1))
                rb.add(step_data)
                # device->host copy overlaps the train dispatch below
                actions_jnp.copy_to_host_async()
        else:
            with tele.span("rollout"):
                actions = np.asarray(actions_jnp)
                step_data["actions"] = actions.reshape(1, num_envs, -1)
                rb.add(step_data)
            with tele.span("env_wait"):
                step_data, obs = fetch_and_step_envs(step_data, obs)

        # in-HBM sequence gather + ratio-1 gradient steps (one per policy
        # step, so num_envs of them per iteration)
        with tele.span("train"):
            for staged in rb.sample(B, sequence_length=T, n_samples=num_envs):
                batch = normalize_staged(staged, obs_keys)
                k_train, sub = jax.random.split(k_train)
                params, opt_states, moments_state, metrics = train_step(
                    params, opt_states, moments_state, batch, sub, jnp.float32(0.02)
                )[:4]

        if pipelined:
            with tele.span("env_wait"):
                step_data, obs = fetch_and_step_envs(step_data, obs)
        return params, opt_states, moments_state, step_data, obs, key, metrics

    results = {}
    for mode, pipelined in (("serialized", False), ("pipelined", True)):
        for _ in range(warmup_iters):
            params, opt_states, moments_state, step_data, obs, key, metrics = one_iter(
                params, opt_states, moments_state, step_data, obs, key, pipelined
            )
        _ = np.asarray(metrics)  # value barrier (see measure_compute note)

        tele.interval_metrics(None)  # drop warmup from the phase accounting
        t0 = time.perf_counter()
        for _ in range(measure_iters):
            params, opt_states, moments_state, step_data, obs, key, metrics = one_iter(
                params, opt_states, moments_state, step_data, obs, key, pipelined
            )
        _ = np.asarray(metrics)
        elapsed = time.perf_counter() - t0
        results[f"grad_steps_per_sec_e2e_{mode}"] = round(measure_iters * num_envs / elapsed, 3)
        if pipelined:  # phase breakdown of the shipped (pipelined) hot loop
            phases = tele.interval_metrics(None)
            results.update(
                {k: round(v, 2) for k, v in phases.items() if k.startswith("Telemetry/phase_pct/")}
            )
            # ISSUE 8: train share of the pipelined e2e window.  Informational
            # — the bench's async-dispatch loop is mostly idle host-side by
            # design, so this is tiny; the LIVE Telemetry/goodput gauge of a
            # real run is the production number.
            results["goodput"] = round(
                phases.get("Telemetry/phase_pct/train", 0.0) / 100.0, 4
            )
    tele.close()  # detach from the process-global compile-listener registry
    envs.close()
    return {
        "grad_steps_per_sec_e2e": results["grad_steps_per_sec_e2e_pipelined"],
        **results,
        "replay": "device (HBM-resident ring)",
    }


def measure_env_overlap(
    precision: str,
    sleep_ms: float = 80.0,
    iters: int = 25,
    warmup_iters: int = 3,
    size: str = "XS",
    batch_size: int = 4,
    sequence_length: int = 16,
):
    """Within-run serialized-vs-pipelined env-overlap pair (ISSUE 2).

    One compiled DV3 train step + one ``sleep_ms`` dummy env through the
    split-phase ``PipelinedVectorEnv`` layer.  ``serialized`` steps the env,
    then dispatches the gradient step and fetches its metrics (the reference
    order); ``pipelined`` issues ``step_async``, dispatches + fetches, and
    only then ``step_wait``s — the env's wall-clock hides behind the train
    dispatch and the blocking metric fetch.  Same graphs, same env, same
    process, back to back, so the tunnel's congestion drift (PERF.md §1)
    cancels within the pair; every timing uses the value-fetch barrier
    discipline of PERF.md §6.  The deterministic ``sleep_ms`` makes the
    expected gap exact: serialized ≈ pipelined + sleep_ms per iteration.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.envs.dummy import DiscreteDummyEnv
    from sheeprl_tpu.envs.env import vectorized_env
    from sheeprl_tpu.envs.pipeline import PipelinedVectorEnv

    _, train_step, state, batch = build_train_step_and_batch(
        precision,
        size=size,
        batch_size=batch_size,
        sequence_length=sequence_length,
        extra_overrides=[
            "algo.cnn_keys.encoder=[]",
            "algo.cnn_keys.decoder=[]",
            "algo.mlp_keys.encoder=[state]",
            "algo.mlp_keys.decoder=[state]",
        ],
    )
    params, opt_states, moments_state = state["params"], state["opt_states"], state["moments_state"]
    key = jax.random.PRNGKey(0)
    tau = jnp.float32(0.02)

    def mk():
        return DiscreteDummyEnv(n_steps=1_000_000, image_size=(3, 8, 8), sleep_ms=sleep_ms)

    envs = PipelinedVectorEnv(vectorized_env([mk], sync=True))
    envs.reset(seed=0)
    actions = np.zeros(1, np.int64)

    def one_iter(pipelined, params, opt_states, moments_state, key):
        key, sub = jax.random.split(key)
        if not pipelined:
            envs.step(actions)
        else:
            envs.step_async(actions)
        params, opt_states, moments_state, metrics = train_step(
            params, opt_states, moments_state, batch, sub, tau
        )[:4]
        _ = np.asarray(metrics)  # per-iter value barrier (PERF.md §6)
        if pipelined:
            envs.step_wait()
        return params, opt_states, moments_state, key

    results = {}
    for mode, pipelined in (("serialized", False), ("pipelined", True)):
        for _ in range(warmup_iters):
            params, opt_states, moments_state, key = one_iter(
                pipelined, params, opt_states, moments_state, key
            )
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_states, moments_state, key = one_iter(
                pipelined, params, opt_states, moments_state, key
            )
        results[f"grad_steps_per_sec_env_{mode}"] = round(iters / (time.perf_counter() - t0), 3)
    envs.close()
    return {
        **results,
        "env_overlap_workload": (
            f"DV3-{size} vector obs, batch {batch_size} x seq {sequence_length}, "
            f"1 dummy env sleep_ms={sleep_ms:g}, thread-backed PipelinedVectorEnv"
        ),
        "env_sleep_ms": sleep_ms,
        "env_overlap_iters": iters,
    }


def measure_env_scale(
    num_envs_list=(4, 16, 64, 256),
    iters: int = 30,
    warmup_iters: int = 3,
    sleep_ms: float = 0.5,
    envs_per_worker=None,
    with_train: bool = True,
    precision: str = "bf16-mixed",
    train_size: str = "XS",
):
    """Many-env player scaling sweep (ISSUE 7): sharded shm executor +
    device-resident batched inference over ``num_envs`` ∈ {4..256}.

    Per env count the loop is the rewired hot-loop shape — stage the batched
    obs slab with ONE ``device_put``, run a tiny jitted policy, fetch the
    actions with ONE blocking ``device_get``, ``step_async``/``step_wait``
    the sharded ``SharedMemoryVectorEnv`` (optionally dispatching a DV3-XS
    gradient step inside the overlap window).  Reported per N:

    * ``env_steps_per_sec`` — N * iters / wall-clock; the acceptance signal
      is monotonic growth 4 → 64 (per-step fixed costs amortize over the
      slab instead of multiplying with it);
    * ``fetch_amortization`` — env steps per blocking d2h fetch (= N by
      construction of the batched-inference path; reported measured, not
      assumed);
    * ``grad_steps_per_sec`` — gradient steps landed inside the env-overlap
      windows (None when ``with_train`` is off, e.g. the CPU liveness probe).

    ``sleep_ms`` gives the dummy envs a deterministic per-step latency so the
    sweep exercises real worker parallelism, not just IPC overhead.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sheeprl_tpu.envs.dummy import DiscreteDummyEnv
    from sheeprl_tpu.envs.executor import SharedMemoryVectorEnv
    from sheeprl_tpu.envs.pipeline import PipelinedVectorEnv

    train_step = state = batch = None
    if with_train:
        _, train_step, state, batch = build_train_step_and_batch(
            precision,
            size=train_size,
            batch_size=4,
            sequence_length=16,
            extra_overrides=[
                "algo.cnn_keys.encoder=[]",
                "algo.cnn_keys.decoder=[]",
                "algo.mlp_keys.encoder=[state]",
                "algo.mlp_keys.decoder=[state]",
            ],
        )
        state["key"] = jax.random.PRNGKey(0)

    key = jax.random.PRNGKey(1)
    w = jax.device_put(jax.random.normal(key, (8, 4), jnp.float32))
    stage_sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])

    @jax.jit
    def policy(w, obs):  # tiny batched policy: [N, 8] -> [N] actions
        return jnp.argmax(obs @ w, axis=-1) % 2

    results = {
        "num_envs": [],
        "env_steps_per_sec": [],
        "fetch_amortization": [],
        "grad_steps_per_sec": [],
        "envs_per_worker": [],
        "sleep_ms": sleep_ms,
        "iters": iters,
    }
    for n in num_envs_list:
        fns = [
            (lambda: DiscreteDummyEnv(n_steps=1_000_000, image_size=(3, 8, 8), vector_shape=(8,), sleep_ms=sleep_ms))
            for _ in range(n)
        ]
        envs = PipelinedVectorEnv(SharedMemoryVectorEnv(fns, envs_per_worker=envs_per_worker))
        try:
            obs, _ = envs.reset(seed=0)

            def one_iter(obs, fetches, grad_steps):
                obs_dev = jax.device_put(
                    np.asarray(obs["state"], np.float32).reshape(n, -1), stage_sharding
                )
                acts = policy(w, obs_dev)
                (actions,) = jax.device_get((acts,))  # the ONE blocking d2h
                fetches += 1
                envs.step_async(actions.astype(np.int64))
                if train_step is not None:
                    state["key"], sub = jax.random.split(state["key"])
                    state["params"], state["opt_states"], state["moments_state"], metrics = train_step(
                        state["params"], state["opt_states"], state["moments_state"], batch, sub, jnp.float32(0.02)
                    )[:4]
                    np.asarray(metrics)  # value barrier inside the overlap window
                    grad_steps += 1
                obs = envs.step_wait()[0]
                return obs, fetches, grad_steps

            fetches = grad_steps = 0
            for _ in range(warmup_iters):
                obs, fetches, grad_steps = one_iter(obs, fetches, grad_steps)
            fetches = grad_steps = 0
            t0 = time.perf_counter()
            for _ in range(iters):
                obs, fetches, grad_steps = one_iter(obs, fetches, grad_steps)
            elapsed = time.perf_counter() - t0
        finally:
            envs.close()
        results["num_envs"].append(int(n))
        results["env_steps_per_sec"].append(round(n * iters / elapsed, 1))
        results["fetch_amortization"].append(round(n * iters / max(1, fetches), 1))
        results["grad_steps_per_sec"].append(
            round(grad_steps / elapsed, 3) if train_step is not None else None
        )
        results["envs_per_worker"].append(int(envs.envs.envs_per_worker))
    sps = results["env_steps_per_sec"]
    upto64 = [v for n, v in zip(results["num_envs"], sps) if n <= 64]
    results["monotonic_4_to_64"] = all(b >= a for a, b in zip(upto64, upto64[1:]))
    return results


def measure_fetch_rtt():
    """Blocking value-fetch round trip of the device link (through the axon
    tunnel this is ~90-110 ms and dominates the e2e loop's critical path; on
    a TPU-VM host it is sub-ms — see PERF.md)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    f = jax.jit(lambda x: x + 1.0)
    x = f(jnp.zeros((256,)))
    np.asarray(x)
    t0 = time.perf_counter()
    for _ in range(10):
        x = f(x)
        np.asarray(x)
    return round((time.perf_counter() - t0) * 100.0, 1)


def measure_learn_health(total_steps: int = 96, timeout_s: float = 240.0):
    """Informational learn-health block for the always-lands JSON (ISSUE 9).

    Runs a tiny vector-only ppo CLI training run in a SUBPROCESS (forced CPU
    — cheap, deterministic, and it cannot disturb this process's initialized
    backend) with the default-on ``diagnostics.health`` layer, then sources
    the block from THAT run's own crash-safe journal: the final policy loss,
    the mean in-graph global grad norm, and how many learning-health
    ``anomaly`` events the detectors journaled.  Not a performance number —
    it exists so every bench round also records whether the instrumented
    loop is *learning-shaped* (finite losses, live gradients, no anomalies).
    """
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    from sheeprl_tpu.diagnostics.journal import read_journal

    repo_root = os.path.dirname(os.path.abspath(__file__))
    overrides = [
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        "env.num_envs=2",
        "env.capture_video=False",
        "buffer.memmap=False",
        "metric.log_level=1",
        "metric.log_every=1",
        "fabric.devices=1",
        "fabric.accelerator=cpu",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.run_test=False",
        "checkpoint.save_last=False",
        f"algo.total_steps={int(total_steps)}",
    ]
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        subprocess.run(
            [sys.executable, os.path.join(repo_root, "sheeprl.py"), *overrides],
            cwd=td,
            env=env,
            check=True,
            timeout=timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        journals = sorted(Path(td).rglob("journal.jsonl"))
        if not journals:
            raise RuntimeError("learn-health drill run left no journal")
        events = read_journal(str(journals[-1]))
    metrics_events = [e for e in events if e.get("event") == "metrics"]
    final_loss = None
    grad_norms = []
    for e in metrics_events:
        m = e.get("metrics") or {}
        loss = m.get("Loss/policy_loss")
        if isinstance(loss, (int, float)):
            final_loss = float(loss)
        gnorm = m.get("Telemetry/health/grad_norm", m.get("Grads/global_norm"))
        if isinstance(gnorm, (int, float)):
            grad_norms.append(float(gnorm))
    return {
        "final_loss": round(final_loss, 6) if final_loss is not None else None,
        "mean_grad_norm": round(sum(grad_norms) / len(grad_norms), 6) if grad_norms else None,
        "anomalies": sum(1 for e in events if e.get("event") == "anomaly"),
        "workload": f"ppo discrete_dummy CPU drill, {int(total_steps)} policy steps",
    }


def measure_offline(
    rows: int = 4096,
    obs_dim: int = 16,
    batch: int = 256,
    read_batches: int = 40,
    drill: bool = True,
    drill_timeout_s: float = 420.0,
):
    """Offline-RL block (ISSUE 15), always-lands: dataset read throughput
    with the host-prefetch thread off vs on, plus offline grad-steps/s
    through the real env-free CLI on the CPU fallback.

    * ``read_sps`` — a synthetic in-memory-sized dataset (``rows`` SAC-shaped
      transitions, sharded) streamed as ``read_batches`` flat batches of
      ``batch`` rows by the deterministic loader, prefetch 0 vs 2.  The pure
      read pair has no device step to hide behind, so prefetch can only add
      queue-handoff overhead here (speedup <= 1 is expected); the drill's
      ``dataset_read_sps`` below is the overlapped number that matters.  The
      batch *sequence* is bit-identical either way (pinned by
      tests/test_offline/);
    * ``drill`` — a tiny SAC collect → ``export_run_dir`` → offline train
      (``algo.offline.enabled=true``, CQL armed) in CPU subprocesses, the
      grad-steps/s sourced from the offline run's own journal
      (``Time/sps_train`` at the last metric interval) — the D4RL-style
      workload measured end-to-end, not as a microbench.
    """
    import shutil
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    import numpy as np

    from sheeprl_tpu.data.buffers import ReplayBuffer
    from sheeprl_tpu.data.datasets import OfflineDataset
    from sheeprl_tpu.offline.export import export_buffer, export_run_dir

    out: dict = {"rows": int(rows), "batch": int(batch)}
    rng = np.random.default_rng(0)
    tmp_root = tempfile.mkdtemp(prefix="bench_offline_")
    try:
        rb = ReplayBuffer(rows, 1, obs_keys=("observations",))
        chunk = 256
        for start in range(0, rows, chunk):
            n = min(chunk, rows - start)
            rb.add(
                {
                    "observations": rng.standard_normal((n, 1, obs_dim)).astype(np.float32),
                    "next_observations": rng.standard_normal((n, 1, obs_dim)).astype(np.float32),
                    "actions": rng.standard_normal((n, 1, 4)).astype(np.float32),
                    "rewards": rng.standard_normal((n, 1, 1)).astype(np.float32),
                    "terminated": np.zeros((n, 1, 1), np.float32),
                    "truncated": np.zeros((n, 1, 1), np.float32),
                }
            )
        export_buffer(rb, os.path.join(tmp_root, "ds"), shard_rows=1024)
        ds = OfflineDataset(os.path.join(tmp_root, "ds"), deep_verify=False)
        for prefetch, label in ((0, "read_sps_no_prefetch"), (2, "read_sps_prefetch")):
            it = ds.batches(batch, seed=1, prefetch=prefetch)
            next(it)  # warm the shard cache / spin the thread up
            t0 = time.perf_counter()
            for _ in range(int(read_batches)):
                next(it)
            out[label] = round(int(read_batches) * batch / (time.perf_counter() - t0), 1)
        if out["read_sps_no_prefetch"] > 0:
            out["prefetch_speedup"] = round(
                out["read_sps_prefetch"] / out["read_sps_no_prefetch"], 3
            )
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)
    if not drill:
        return out

    repo_root = os.path.dirname(os.path.abspath(__file__))
    common = [
        "exp=sac",
        "env=dummy",
        "env.id=continuous_dummy",
        "env.num_envs=2",
        "env.capture_video=False",
        "buffer.memmap=False",
        "buffer.size=128",
        "metric.log_level=1",
        "metric.log_every=1",
        "fabric.devices=1",
        "fabric.accelerator=cpu",
        "algo.per_rank_batch_size=16",
        "algo.mlp_keys.encoder=[state]",
        "algo.run_test=False",
        "checkpoint.save_last=True",
    ]
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        subprocess.run(
            [
                sys.executable,
                os.path.join(repo_root, "sheeprl.py"),
                *common,
                "algo.total_steps=64",
                "algo.learning_starts=1000",  # prefill-only collect
                "buffer.checkpoint=True",
                "run_name=bench_collect",
            ],
            cwd=td,
            env=env,
            check=True,
            timeout=drill_timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        collect_dir = Path(td) / "logs" / "runs" / "sac" / "continuous_dummy" / "bench_collect"
        exported = export_run_dir(str(collect_dir), shard_rows=1024)
        out["drill_dataset_rows"] = exported["rows"]
        subprocess.run(
            [
                sys.executable,
                os.path.join(repo_root, "sheeprl.py"),
                *common,
                "algo.total_steps=96",
                "run_name=bench_offline",
                "algo.offline.enabled=true",
                f"algo.offline.dataset_dir={exported['path']}",
                "algo.offline.grad_steps_per_iter=4",  # 16x4=64 rows/draw == the collected set
                "algo.offline.cql_alpha=0.5",
            ],
            cwd=td,
            env=env,
            check=True,
            timeout=drill_timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        from sheeprl_tpu.diagnostics.journal import find_journal, read_journal

        journal = find_journal(str(collect_dir.parent / "bench_offline"))
        if journal is None:
            raise RuntimeError("offline drill run left no journal")
        events = read_journal(journal)
        metrics_events = [e for e in events if e.get("event") == "metrics"]
        last = (metrics_events[-1].get("metrics") or {}) if metrics_events else {}
        out["drill_grad_steps_per_sec"] = (
            round(float(last["Time/sps_train"]), 3)
            if isinstance(last.get("Time/sps_train"), (int, float))
            else None
        )
        out["drill_dataset_read_sps"] = (
            round(float(last["Telemetry/dataset_read_sps"]), 1)
            if isinstance(last.get("Telemetry/dataset_read_sps"), (int, float))
            else None
        )
        losses = [
            last.get(k)
            for k in ("Loss/value_loss", "Loss/policy_loss", "Loss/alpha_loss")
            if isinstance(last.get(k), (int, float))
        ]
        out["drill_losses_finite"] = bool(losses) and all(np.isfinite(v) for v in losses)
        out["drill_shards_skipped"] = sum(
            1 for e in events if e.get("event") == "dataset_shard_skipped"
        )
        out["workload"] = "sac offline, batch 16 x 4 grad-steps/iter, cql_alpha 0.5, CPU drill"
    return out


def measure_recovery(
    state_mb: float = 32.0,
    interval_iters: int = 12,
    train_tick_s: float = 0.01,
    kill_drill: bool = True,
    drill_timeout_s: float = 420.0,
):
    """Resilience block (ISSUE 13), always-lands: checkpoint cost on vs off
    the critical path, and measured time-to-recover from one injected kill.

    * ``blocking_write_ms`` vs ``async_critical_path_ms`` — one ~``state_mb``
      synthetic state saved synchronously (serialize+fsync on the caller)
      vs submitted to the :class:`AsyncCheckpointWriter` (the caller pays
      only the host snapshot + enqueue);
    * ``interval_goodput`` — a simulated checkpointing interval
      (``interval_iters`` train ticks of ``train_tick_s``, one checkpoint
      every 4 ticks): productive share of wall-clock with blocking saves vs
      the async writer overlapping them — the mechanism behind the
      acceptance claim that async checkpointing raises train-span goodput;
    * ``kill_drill`` — a tiny supervised ppo CLI run (CPU subprocess) whose
      first child is SIGKILLed by ``tools/supervise.py
      --kill-after-first-checkpoint`` the moment a verified checkpoint
      exists, auto-restarted, and resumed to completion; time-to-recover and
      the segment labels come from ``tools/goodput_report.py``'s own
      analysis of the run's journals.
    """
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    from sheeprl_tpu.resilience.async_writer import AsyncCheckpointWriter
    from sheeprl_tpu.resilience.manifest import save_verified_checkpoint

    import numpy as np

    repo_root = os.path.dirname(os.path.abspath(__file__))
    n = max(1, int(state_mb * (1 << 20) / 4))
    rng = np.random.default_rng(0)
    state = {"params": {"w": rng.standard_normal(n).astype(np.float32)}, "policy_step": 1}
    out: dict = {"state_bytes": n * 4}
    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        save_verified_checkpoint(os.path.join(td, "ckpt_1_0.ckpt"), state)
        out["blocking_write_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        writer = AsyncCheckpointWriter()
        t0 = time.perf_counter()
        crit_s = writer.submit(os.path.join(td, "ckpt_2_0.ckpt"), state, step=2)
        out["async_critical_path_ms"] = round(crit_s * 1e3, 3)
        writer.drain()
        writer.close()
        out["async_write_ms"] = writer.stats()["last_write_ms"]
        if out["async_critical_path_ms"] > 0:
            out["critical_path_speedup"] = round(
                out["blocking_write_ms"] / out["async_critical_path_ms"], 2
            )

        def interval_goodput(use_async: bool) -> float:
            ckpt_dir = os.path.join(td, "async" if use_async else "blocking")
            interval_writer = AsyncCheckpointWriter() if use_async else None
            wall0 = time.perf_counter()
            train_s = 0.0
            for i in range(int(interval_iters)):
                t = time.perf_counter()
                time.sleep(train_tick_s)  # stands in for the train span
                train_s += time.perf_counter() - t
                if i % 4 == 3:
                    path = os.path.join(ckpt_dir, f"ckpt_{i}_0.ckpt")
                    if interval_writer is not None:
                        interval_writer.submit(path, state, step=i)
                    else:
                        save_verified_checkpoint(path, state, step=i)
            wall = time.perf_counter() - wall0
            if interval_writer is not None:
                interval_writer.close()  # writes finish off the measured window
            return round(train_s / wall, 4) if wall > 0 else 0.0

        out["interval_goodput"] = {
            "blocking": interval_goodput(False),
            "async": interval_goodput(True),
        }

    if not kill_drill:
        return out
    overrides = [
        "exp=ppo",
        "env=dummy",
        "env.id=discrete_dummy",
        "env.num_envs=2",
        "env.capture_video=False",
        "buffer.memmap=False",
        "metric.log_level=1",
        "metric.log_every=1",
        "fabric.devices=1",
        "fabric.accelerator=cpu",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.run_test=False",
        "run_name=bench_recovery",
        "algo.total_steps=512",
        "checkpoint.every=16",
        "checkpoint.save_last=False",
    ]
    with tempfile.TemporaryDirectory() as td:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [
                sys.executable,
                os.path.join(repo_root, "tools", "supervise.py"),
                "--max-restarts",
                "2",
                "--backoff",
                "0.5",
                "--kill-after-first-checkpoint",
                *overrides,
            ],
            cwd=td,
            env=env,
            timeout=drill_timeout_s,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        run_dir = Path(td) / "logs" / "runs" / "ppo" / "discrete_dummy" / "bench_recovery"
        sys.path.insert(0, os.path.join(repo_root, "tools"))
        try:
            from goodput_report import analyze_segments, read_supervisor

            from sheeprl_tpu.diagnostics.journal import collect_journals

            journals = collect_journals([str(run_dir)])
            analysis = analyze_segments(journals)
            supervisor = read_supervisor(str(run_dir))
        finally:
            sys.path.pop(0)
        out["kill_drill"] = {
            "supervise_rc": proc.returncode,
            "segments": [s["label"] for s in analysis["segments"]],
            "time_to_recover_s": analysis["time_to_recover_s"],
            "recovered_train_s": analysis["recovered_train_s"],
            "restarts": (supervisor or {}).get("restarts"),
            "measured_down_s": (supervisor or {}).get("measured_down_s"),
        }
    return out


def measure_decoupled(iters: int = 8, timeout_s: float = 420.0):
    """Decoupled-topology overhead pair (VERDICT item 7), always-lands:
    coupled PPO on a 7-device mesh vs decoupled PPO at 1 player + 7 trainers
    on an 8-device mesh — same 7-way trainer parallelism, same per-device
    minibatch (56-sample rollouts, batch 8), so the pair isolates exactly
    what decoupling adds: the rollout scatter onto the trainer sub-mesh and
    the params hop back to the player.

    Both runs are subprocesses on a FORCED virtual-8-device CPU platform
    (``--xla_force_host_platform_device_count=8`` — the dryrun-validated
    MULTICHIP topology), so the block lands identically on chip rounds and
    dead-tunnel rounds: a pathological serialization regression in the
    decoupled loop is caught before real hardware ever sees it.  Steady-state
    per-iteration wall times come from each run's own journal (`metrics`
    event timestamps at ``log_every=1``), first two iterations dropped as
    compile tail.  CPU liveness numbers — the overhead RATIO is the signal,
    not the absolute iters/s.
    """
    import subprocess
    import sys
    import tempfile
    from pathlib import Path

    from sheeprl_tpu.diagnostics.journal import read_journal

    total_steps = 14 * 4 * int(iters)
    common = [
        "env=dummy",
        "env.id=discrete_dummy",
        "env.num_envs=4",
        "env.capture_video=False",
        "buffer.memmap=False",
        "metric.log_level=1",
        "metric.log_every=1",
        "fabric.accelerator=cpu",
        "algo.rollout_steps=14",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.dense_units=16",
        "algo.mlp_layers=1",
        "algo.mlp_keys.encoder=[state]",
        "algo.cnn_keys.encoder=[]",
        "algo.run_test=False",
        f"algo.total_steps={total_steps}",
        "checkpoint.every=0",
        "checkpoint.save_last=False",
    ]
    variants = {
        "coupled": ["exp=ppo", "fabric.devices=7"],
        "decoupled": ["exp=ppo_decoupled", "fabric.devices=8"],
    }
    out: dict = {
        "workload": (
            "ppo discrete_dummy, 56-sample rollouts (14 steps x 4 envs), batch 8, "
            f"{iters} iters on the virtual 8-device CPU mesh: coupled@7dev vs decoupled@1+7"
        )
    }
    from sheeprl_tpu.utils.utils import subprocess_cli_env

    env = subprocess_cli_env(device_count=8)
    for name, extra in variants.items():
        with tempfile.TemporaryDirectory() as td:
            proc = subprocess.run(
                [sys.executable, "-m", "sheeprl_tpu", *extra, *common, f"run_name=bench_{name}"],
                cwd=td,
                env=env,
                timeout=timeout_s,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
            journals = sorted(Path(td).rglob("journal.jsonl"))
            events = read_journal(str(journals[0])) if journals else []
            stamps = [
                e["t"] for e in events if e.get("event") == "metrics" and isinstance(e.get("t"), (int, float))
            ]
            gaps = sorted(b - a for a, b in zip(stamps, stamps[1:]))[: max(1, len(stamps) - 3)]
            # median of the steady-state gaps (compile-inflated outliers are
            # the largest gaps, already clipped off the sorted tail above).
            # A crashed child (rc != 0) publishes NO timing: a partial run's
            # gaps would read as a plausible regression/improvement signal.
            steady = gaps[len(gaps) // 2] if gaps and proc.returncode == 0 else None
            out[name] = {
                "rc": proc.returncode,
                "n_iters_logged": len(stamps),
                "steady_iter_ms": round(steady * 1e3, 1) if steady else None,
                "iters_per_sec": round(1.0 / steady, 2) if steady else None,
            }
    coupled_ms = (out.get("coupled") or {}).get("steady_iter_ms")
    decoupled_ms = (out.get("decoupled") or {}).get("steady_iter_ms")
    if coupled_ms and decoupled_ms:
        # > 1.0 = decoupling costs; the scatter + params-hop overhead line
        out["decoupled_vs_coupled_iter_ratio"] = round(decoupled_ms / coupled_ms, 3)
    return out


_FSDP_CHILD_SRC = r"""
import json, sys, time
import numpy as np
import jax
import jax.numpy as jnp

size, precision = sys.argv[1], sys.argv[2]
batch_size, seq_len, iters = int(sys.argv[3]), int(sys.argv[4]), int(sys.argv[5])

from bench import build_train_step_and_batch
from sheeprl_tpu.parallel.dp import stage
from sheeprl_tpu.parallel.fsdp import shard_tree, tree_bytes_per_device
from sheeprl_tpu.parallel.mesh import make_mesh, replicated_sharding

def tree_bytes(t):
    return sum(int(np.prod(x.shape)) * x.dtype.itemsize for x in jax.tree_util.tree_leaves(t))

MIN_SHARD = 1024
out = {}
meshes = {
    "dp": make_mesh(n_devices=8, axis_names=("data",)),
    "fsdp": make_mesh(n_devices=8, axis_names=("data", "model"), axis_sizes=(1, 8)),
}
for name, mesh in meshes.items():
    cfg, step, state, batch = build_train_step_and_batch(
        precision, size=size, batch_size=batch_size, sequence_length=seq_len,
        extra_overrides=["distribution.fsdp_min_shard_bytes=%d" % MIN_SHARD], mesh=mesh,
    )
    params, opt_states, moments = state["params"], state["opt_states"], state["moments_state"]
    if name == "fsdp":
        params = shard_tree(params, mesh, MIN_SHARD)
        opt_states = shard_tree(opt_states, mesh, MIN_SHARD)
    else:
        params = jax.device_put(params, replicated_sharding(mesh))
        opt_states = jax.device_put(opt_states, replicated_sharding(mesh))
    moments = jax.device_put(moments, replicated_sharding(mesh))
    batch = stage({k: np.asarray(v) for k, v in batch.items()}, mesh, batch_axis=1)
    key = jax.random.PRNGKey(0)
    tau = jnp.float32(0.02)
    for _ in range(2):
        key, sub = jax.random.split(key)
        params, opt_states, moments, metrics = step(params, opt_states, moments, batch, sub, tau)[:4]
    np.asarray(metrics)
    t0 = time.perf_counter()
    for _ in range(iters):
        key, sub = jax.random.split(key)
        params, opt_states, moments, metrics = step(params, opt_states, moments, batch, sub, tau)[:4]
    final = np.asarray(metrics)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(final).all(), name
    out[name] = {
        "step_ms": round(elapsed / iters * 1e3, 2),
        "params_bytes": tree_bytes(params),
        "params_bytes_per_device": tree_bytes_per_device(params),
        "opt_bytes_per_device": tree_bytes_per_device(opt_states),
    }
print("BENCH_FSDP_JSON " + json.dumps(out), flush=True)
"""


def measure_fsdp(
    precision: str,
    size: str = "XS",
    batch_size: int = 8,
    sequence_length: int = 8,
    iters: int = 4,
    timeout_s: float = 420.0,
):
    """FSDP-vs-DP pair (ISSUE 17), always-lands: the SAME DV3 train step on
    the virtual 8-device CPU mesh twice — replicated state over a 1-D
    ``("data",)`` mesh (shard_map DP) vs partition-rule-sharded state over a
    2-D ``(1, 8)`` ``("data", "model")`` mesh (global-view FSDP jit) — same
    global batch, so the pair isolates exactly what sharding the train state
    costs in step time and buys in per-device bytes.

    One subprocess runs both variants (``subprocess_cli_env`` forces the
    8-device virtual platform regardless of the parent's backend), so the
    block lands identically on chip rounds and dead-tunnel rounds.  CPU
    liveness numbers — ``params_per_device_shrink`` is the memory signal and
    ``fsdp_vs_dp_step_ratio`` the serialization canary, not the absolute ms.
    """
    import re
    import subprocess
    import sys

    from sheeprl_tpu.utils.utils import subprocess_cli_env

    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            _FSDP_CHILD_SRC,
            size,
            precision,
            str(batch_size),
            str(sequence_length),
            str(iters),
        ],
        env=subprocess_cli_env(device_count=8),
        timeout=timeout_s,
        capture_output=True,
        text=True,
    )
    out: dict = {
        "workload": (
            f"dreamer_v3_{size} pixels, batch {batch_size} x seq {sequence_length}, "
            f"{iters} iters on the virtual 8-device CPU mesh: replicated DP@8 vs "
            "FSDP (1x8 model axis, min_shard_bytes=1024)"
        ),
        "rc": proc.returncode,
    }
    m = re.search(r"^BENCH_FSDP_JSON (.*)$", proc.stdout, re.MULTILINE)
    if proc.returncode != 0 or m is None:
        # a crashed child publishes NO timing (the measure_decoupled lesson);
        # the stderr tail makes the failure diagnosable from the JSON line
        out["error"] = (proc.stderr or proc.stdout or "")[-400:]
        return out
    out.update(json.loads(m.group(1)))
    dp, fsdp = out.get("dp") or {}, out.get("fsdp") or {}
    if dp.get("step_ms") and fsdp.get("step_ms"):
        # > 1.0 = sharding costs step time (gather/scatter on the critical path)
        out["fsdp_vs_dp_step_ratio"] = round(fsdp["step_ms"] / dp["step_ms"], 3)
    if dp.get("params_bytes_per_device") and fsdp.get("params_bytes_per_device"):
        # ~axis_size = the ZeRO-3 memory win; < axis_size means replicated
        # small leaves (below min_shard_bytes or with no divisible dim)
        out["params_per_device_shrink"] = round(
            dp["params_bytes_per_device"] / fsdp["params_bytes_per_device"], 2
        )
    return out


def measure_serving(
    loads=(1, 4, 16),
    duration_s: float = 3.0,
    buckets=(4, 8, 16),
    max_delay_ms: float = 2.0,
):
    """Serving-tier block (ISSUE 11): requests/sec, p50/p99 latency and mean
    batch width at several offered-load points, measured through the REAL
    HTTP tier (``POST /act``) by an in-process client swarm.  Each point also
    carries the per-phase breakdown (queue/dispatch p50·p99) and the SLO
    burn-rate gauge from the service's phase stats, and the overload point
    reports the mean shed-wait (ISSUE 19).

    The policy is a tiny randomly-initialized vector ppo agent — serving
    throughput is a property of the batcher + compiled-step pipeline, not of
    the weights, so no checkpoint/training is needed and the block lands on
    the CPU-fallback path too (callers pass the smallest load there).
    """
    import json as _json
    import threading
    import urllib.request

    import gymnasium as gym
    import numpy as np

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.serving.loader import build_policy
    from sheeprl_tpu.serving.server import PolicyService

    cfg = compose(
        [
            "exp=ppo",
            "env=dummy",
            "env.id=discrete_dummy",
            "algo.mlp_keys.encoder=[state]",
            "algo.cnn_keys.encoder=[]",
            "algo.dense_units=64",
            "algo.mlp_layers=2",
        ]
    )
    obs_dim = 10
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-20, 20, (obs_dim,), np.float32)})
    handle = build_policy(cfg, obs_space, gym.spaces.Discrete(6))
    service = PolicyService(
        handle,
        {
            "batch_buckets": list(buckets),
            "max_delay_ms": float(max_delay_ms),
            # an SLO target so each point also reports the burn-rate gauge
            # (ISSUE 19); generous enough that a healthy CPU box sits near 0
            "slo": {"target_ms": 250.0, "objective": 0.99},
        },
    )
    service.start()
    service.warmup()

    # a minimal HTTP tier rather than direct service calls: latency numbers
    # include JSON parse + socket turnaround, like a production client sees
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # noqa: ANN001
            pass

        def do_POST(self):  # noqa: N802
            try:
                length = int(self.headers.get("Content-Length") or 0)
                payload = _json.loads(self.rfile.read(length) or b"{}")
                result = service.act(payload["obs"])
                status, body = 200, _json.dumps(
                    {"action": np.asarray(result["action"]).tolist()}
                ).encode()
            except Exception as err:  # noqa: BLE001 — a failed request must
                # answer 500, not kill the connection (and with it the swarm
                # client thread whose load the point claims to measure)
                status, body = 500, _json.dumps({"error": repr(err)}).encode()
            self.send_response(status)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
    httpd.daemon_threads = True
    http_thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    http_thread.start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}/act"

    def swarm(n_clients: int) -> dict:
        payload = _json.dumps(
            {"obs": {"state": np.linspace(-1, 1, obs_dim).tolist()}}
        ).encode()
        before = service.batcher.stats()
        stop_t = time.monotonic() + duration_s
        # per-WINDOW latency samples, measured client-side: the batcher's own
        # percentile deque is service-lifetime, so reading it here would let
        # earlier (lower-load) points dilute this point's tail
        samples = [[] for _ in range(n_clients)]

        client_errors = [0] * n_clients

        def client(i: int) -> None:
            while time.monotonic() < stop_t:
                t_req = time.perf_counter()
                try:
                    with urllib.request.urlopen(
                        urllib.request.Request(url, data=payload), timeout=30
                    ) as resp:
                        resp.read()
                except Exception:  # noqa: BLE001 — keep offering load; the
                    client_errors[i] += 1  # point reports the error count
                    continue
                samples[i].append((time.perf_counter() - t_req) * 1000.0)

        threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        after = service.batcher.stats()
        d_req = after["requests_total"] - before["requests_total"]
        d_disp = after["dispatches_total"] - before["dispatches_total"]
        latencies = sorted(v for chunk in samples for v in chunk)

        def pct(p: float):
            if not latencies:
                return None
            rank = min(len(latencies) - 1, int(round(p / 100.0 * (len(latencies) - 1))))
            return round(latencies[rank], 3)

        # per-phase breakdown + SLO burn from the service's own phase stats
        # (ISSUE 19): the rolling window is dominated by this point's traffic
        # (each point issues far more requests than the window holds), so the
        # snapshot right after the swarm is this point's breakdown
        gauges = (service.snapshot().get("gauges") or {})
        return {
            "clients": n_clients,
            "requests_per_sec": round(len(latencies) / wall, 2) if wall > 0 else None,
            "latency_p50_ms": pct(50.0),
            "latency_p99_ms": pct(99.0),
            "batch_width_mean": round(d_req / d_disp, 3) if d_disp else None,
            "errors": sum(client_errors),
            "queue_ms_p50": gauges.get("Telemetry/serve/queue_ms_p50"),
            "queue_ms_p99": gauges.get("Telemetry/serve/queue_ms_p99"),
            "dispatch_ms_p50": gauges.get("Telemetry/serve/dispatch_ms_p50"),
            "dispatch_ms_p99": gauges.get("Telemetry/serve/dispatch_ms_p99"),
            "slo_burn": gauges.get("Telemetry/serve/slo_burn"),
        }

    def overload_point(offered: int = 32, queue_limit: int = 4) -> dict:
        """Load shedding at the door (ISSUE 16): shrink the request queue,
        slow the dispatcher with its test seam, offer more concurrent
        requests than slots and count the 503s.  Shed requests carry the
        batcher's advisory ``Retry-After`` (seconds) — reported so the
        overload contract is visible in the bench artifact."""
        before = service.batcher.stats()
        old_queue = service.batcher.max_queue
        service.batcher.max_queue = int(queue_limit)
        service._step_delay_s = 0.05
        obs = {"state": np.linspace(-1, 1, obs_dim).tolist()}
        lock = threading.Lock()
        outcome = {"ok": 0, "shed": 0, "retry_after": []}

        def client() -> None:
            try:
                service.act(obs, timeout_s=10.0)
                with lock:
                    outcome["ok"] += 1
            except Exception as err:  # noqa: BLE001 — 503s are the point
                with lock:
                    outcome["shed"] += 1
                    retry_after = getattr(err, "retry_after", None)
                    if retry_after is not None:
                        outcome["retry_after"].append(retry_after)

        threads = [threading.Thread(target=client) for _ in range(int(offered))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        service._step_delay_s = None
        service.batcher.max_queue = old_queue
        after = service.batcher.stats()
        return {
            "offered": int(offered),
            "queue_limit": int(queue_limit),
            "accepted": outcome["ok"],
            "shed_503": outcome["shed"],
            "shed_total_delta": after["shed_total"] - before["shed_total"],
            # mean time a shed request sat queued before its 503 (ISSUE 19):
            # the client-visible cost of hitting the full queue
            "shed_wait_ms": after.get("shed_wait_ms"),
            "retry_after_s": sorted(set(outcome["retry_after"])) or None,
        }

    try:
        points = [swarm(int(n)) for n in loads]
        overload = overload_point()
    finally:
        httpd.shutdown()
        httpd.server_close()
        http_thread.join(timeout=5)
        service.close()
    return {
        "buckets": list(buckets),
        "max_delay_ms": float(max_delay_ms),
        "compiles": service.compile_count,
        "points": points,
        "overload": overload,
    }


def _ensure_responsive_device():
    """Probe device enumeration in a SUBPROCESS with a timeout: a hung remote
    accelerator (the axon tunnel drops out for minutes at a time — PERF.md
    §1) would otherwise block ``jax.devices()`` forever and hang the whole
    bench.  On a dead tunnel, fall back to CPU so the harness still reports
    a (clearly labeled) result."""
    import subprocess
    import sys

    reason = None
    # Popen + poll instead of subprocess.run: a probe child hung on a dead
    # tunnel can be in UNKILLABLE D-state (stuck in the device driver), and
    # run()'s TimeoutExpired cleanup then blocks forever in process.wait() —
    # the probe itself would hang the bench it exists to protect.  The probe
    # prints the resolved PLATFORM, not just liveness: a responsive backend
    # that turns out to be the CPU (site plugin silently falling back, or a
    # forced-cpu environment) must take the CPU-fallback workload too — the
    # flagship pixel menu on one CPU core burns the whole budget for nothing
    # (exactly what a responsive-but-CPU probe let happen before r6).
    import tempfile

    probe_out = tempfile.NamedTemporaryFile(mode="w+", suffix=".txt", delete=False)
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; print(jax.devices()[0].platform)"],
        stdout=probe_out,
        stderr=subprocess.DEVNULL,
    )
    try:
        rc = proc.wait(timeout=180)
        if rc == 0:
            probe_out.seek(0)
            platform = probe_out.read().strip().lower()
            if platform and platform != "cpu":
                return None
            reason = f"no accelerator behind the responsive backend (platform={platform or '?'})"
        else:
            reason = f"device enumeration failed (exit {rc})"
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass  # D-state child: abandon it rather than wait forever
        reason = "accelerator link unresponsive (enumeration timed out)"
    finally:
        probe_out.close()
        try:
            os.unlink(probe_out.name)
        except OSError:
            pass
    print(f"WARNING: {reason}; benching on CPU", file=sys.stderr)
    import jax

    jax.config.update("jax_platforms", "cpu")
    return (
        f"{reason} at bench time - these are CPU-fallback numbers; "
        "chip numbers are recorded in PERF.md and prior BENCH_r* files"
    )


def _run_cpu_fallback(record: dict, precision: str) -> None:
    """Tiny workload for a dead accelerator link: DV3-XS, vector obs, short
    sequences, few iterations — finishes in ~2 minutes on one CPU core.  The
    WORKLOAD degrades, not just the label (VERDICT r4 weak #1: the full
    pixel menu is hopeless on CPU and round 4's fallback timed out in the
    driver).  ``value`` is a liveness number, explicitly not comparable to
    the RTX-3080 baseline; chip numbers live in PERF.md and prior BENCH_r*."""
    record["workload"] = (
        "CPU-fallback liveness probe: DV3-XS, vector obs, batch 4 x seq 16, "
        "20 iters — NOT the flagship pixel workload and not comparable to "
        "the baseline; driver-verified chip numbers are in prior BENCH_r* "
        "files and PERF.md"
    )
    # distinct metric name so cross-round aggregation by "metric" never mixes
    # this liveness number into the DV3-S chip series
    record["metric"] = "dreamer_v3_cpu_fallback_liveness_grad_steps_per_sec"
    record["unit"] = "grad-steps/s end-to-end (CPU fallback: DV3-XS vector, batch 4 x seq 16, ratio 1)"
    record["vs_baseline"] = None
    record["baseline"] = None  # the RTX-3080 DV3-S baseline does not apply to the liveness workload
    record["fetch_rtt_ms"] = measure_fetch_rtt()
    e2e = measure_e2e(
        precision,
        size="XS",
        batch_size=4,
        sequence_length=16,
        pixels=False,
        warmup_iters=3,
        measure_iters=20,
    )
    record["value"] = e2e["grad_steps_per_sec_e2e"]
    record.update({k: v for k, v in e2e.items() if k != "grad_steps_per_sec_e2e"})
    # pinned CPU floor (VERDICT item 5): consecutive dead-tunnel rounds still
    # get regression detection — a non-null comparison with its contention
    # caveat attached, never the chip `vs_baseline`
    record["cpu_baseline"] = {
        "floor_grad_steps_per_sec": CPU_FALLBACK_FLOOR_GRAD_STEPS_PER_SEC,
        "caveat": CPU_FALLBACK_FLOOR_CAVEAT,
    }
    if isinstance(record["value"], (int, float)) and record["value"] > 0:
        record["vs_cpu_baseline"] = round(
            record["value"] / CPU_FALLBACK_FLOOR_GRAD_STEPS_PER_SEC, 3
        )
    else:
        record["vs_cpu_baseline"] = None
    # tiny compute stage so the Telemetry/* alias fields (mfu, tflops/s —
    # same names the live layer journals) land even on the fallback path;
    # the MFU is against the assumed v5e peak and explicitly marked as such
    try:
        compute = measure_compute(
            precision,
            size="XS",
            batch_size=4,
            measure_steps=10,
            extra_overrides=[
                "algo.per_rank_sequence_length=16",
                "algo.cnn_keys.encoder=[]",
                "algo.cnn_keys.decoder=[]",
                "algo.mlp_keys.encoder=[state]",
                "algo.mlp_keys.decoder=[state]",
            ],
        )
        record.update({k: v for k, v in compute.items() if k != "grad_steps_per_sec_compute"})
        record["grad_steps_per_sec_compute_XS"] = compute["grad_steps_per_sec_compute"]
    except Exception as err:  # noqa: BLE001 — the liveness number must land regardless
        record.setdefault("stage_errors", {})["compute_XS"] = repr(err)
    # env-scale sanity probe (CPU): smaller sweep, no gradient steps — the
    # sleep_ms dummy sweep still proves env_steps_per_sec monotonicity and
    # lands the fields so cross-round JSON aggregation never misses them
    try:
        record["env_scale"] = measure_env_scale(
            num_envs_list=(4, 16, 64), iters=12, sleep_ms=0.5, with_train=False
        )
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["env_scale"] = repr(err)
    # MFU-lever sweep, smallest point (ROADMAP item 2): base vs rssm_chunks=2
    # on the XS vector workload — a liveness proof that the chunked graph
    # compiles, trains finite and lands its JSON fields; chip truth for the
    # full lever menu comes from the chip-menu stage at XL shapes
    try:
        record["mfu_levers"] = measure_mfu_levers(
            precision,
            size="XS",
            batch_size=4,
            sequence_length=16,
            measure_steps=4,
            variants={
                "base": [
                    "algo.cnn_keys.encoder=[]",
                    "algo.cnn_keys.decoder=[]",
                    "algo.mlp_keys.encoder=[state]",
                    "algo.mlp_keys.decoder=[state]",
                ],
                "rssm_chunks2": [
                    "algo.cnn_keys.encoder=[]",
                    "algo.cnn_keys.decoder=[]",
                    "algo.mlp_keys.encoder=[state]",
                    "algo.mlp_keys.decoder=[state]",
                    "algo.rssm_chunks=2",
                ],
            },
        )
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["mfu_levers"] = repr(err)
    # learn-health block (ISSUE 9): sourced from a tiny CLI drill run's own
    # journal — informational, lands on the fallback path too
    try:
        record["learn_health"] = measure_learn_health()
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["learn_health"] = repr(err)
    # serving block (ISSUE 11): the smallest offered load only — one CPU core
    # serving and swarming at once makes larger loads pure queueing noise
    try:
        record["serving"] = measure_serving(loads=(2,), duration_s=1.5, buckets=(2, 4))
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["serving"] = repr(err)
    # recovery block (ISSUE 13): async-vs-blocking checkpoint cost + one
    # supervised injected-kill cycle, both CPU-native — lands on the
    # fallback path by design (the acceptance numbers are CPU numbers)
    try:
        record["recovery"] = measure_recovery(state_mb=8.0)
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["recovery"] = repr(err)
    # decoupled-topology overhead pair (ISSUE 14 / VERDICT item 7): CPU
    # virtual-mesh subprocesses by design — lands on the fallback path too
    try:
        record["decoupled"] = measure_decoupled()
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["decoupled"] = repr(err)
    # offline-RL block (ISSUE 15): loader read-sps prefetch pair + the
    # env-free grad-steps/s drill — CPU-native by design, lands here too
    try:
        record["offline"] = measure_offline()
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["offline"] = repr(err)
    # FSDP-vs-DP pair (ISSUE 17): per-device param/opt bytes and step-time
    # ratio on the virtual 8-device mesh — a CPU subprocess by design, lands
    # on the fallback path at the XS vector-free pixel shapes
    try:
        record["fsdp"] = measure_fsdp(precision, size="XS", batch_size=8, sequence_length=8, iters=4)
    except Exception as err:  # noqa: BLE001
        record.setdefault("stage_errors", {})["fsdp"] = repr(err)


def _run_chip_menu(record: dict, precision: str, deadline: float) -> None:
    """Full flagship menu, stage by stage, newest-information-first under a
    wall-clock budget: the headline e2e lands first, optional stages are
    skipped (and named in ``skipped``) once the budget runs low, and a stage
    failure is recorded without killing the stages after it."""
    record["fetch_rtt_ms"] = measure_fetch_rtt()

    def remaining() -> float:
        return deadline - time.monotonic()

    def stage(name: str, est_s: float, fn):
        if remaining() < est_s:
            record.setdefault("skipped", []).append(f"{name} (budget: {int(remaining())}s left < est {int(est_s)}s)")
            return None
        try:
            return fn()
        except Exception as err:  # noqa: BLE001 — a failed stage must not kill the menu
            record.setdefault("stage_errors", {})[name] = repr(err)
            return None

    # headline stage runs under jax.transfer_guard("log") with fd-level
    # stderr capture: the runtime's transfer-log lines are the only faithful
    # implicit-transfer counter (the guard logs from C++).  None = capture
    # unavailable; the e2e number lands regardless.
    def _guarded_e2e():
        from sheeprl_tpu.diagnostics.memory import count_guard_log_lines

        result, transfers = count_guard_log_lines(lambda: measure_e2e(precision))
        record["host_transfer_count"] = transfers
        return result

    e2e = stage("e2e_S", 240, _guarded_e2e)
    if e2e:
        record["value"] = e2e["grad_steps_per_sec_e2e"]
        record["vs_baseline"] = round(record["value"] / BASELINE_E2E_GRAD_STEPS_PER_SEC, 3)
        record.update({k: v for k, v in e2e.items() if k != "grad_steps_per_sec_e2e"})

    compute = stage("compute_S", 180, lambda: measure_compute(precision))
    if compute:
        record.update(compute)

    # 4-env variant: one action fetch serves 4 policy steps, amortizing the
    # device-link round trip that bounds the 1-env loop (PERF.md §2); still
    # ratio 1 — four gradient steps per iteration
    e2e_4env = stage("e2e_S_4env", 240, lambda: measure_e2e(precision, num_envs=4))
    if e2e_4env:
        record["grad_steps_per_sec_e2e_4env"] = e2e_4env["grad_steps_per_sec_e2e_pipelined"]
        record["grad_steps_per_sec_e2e_4env_serialized"] = e2e_4env["grad_steps_per_sec_e2e_serialized"]

    # split-phase env pipeline pair (ISSUE 2): same compiled step + same env,
    # serialized vs step_async/step_wait, within one run so tunnel drift
    # cancels; fetch_rtt_ms above carries the pair's tunnel context
    env_overlap = stage("env_overlap", 240, lambda: measure_env_overlap(precision))
    if env_overlap:
        record["grad_steps_per_sec_env_serialized"] = env_overlap["grad_steps_per_sec_env_serialized"]
        record["grad_steps_per_sec_env_pipelined"] = env_overlap["grad_steps_per_sec_env_pipelined"]
        record.update({k: v for k, v in env_overlap.items() if not k.startswith("grad_steps")})

    # many-env player scaling sweep (ISSUE 7): sharded shm executor +
    # batched inference over num_envs 4..256, DV3-XS grad steps inside the
    # overlap windows; the acceptance signal is env_steps_per_sec growing
    # monotonically 4 -> 64 with fetch amortization >= 16x at 64 envs
    env_scale = stage("env_scale", 300, lambda: measure_env_scale(precision=precision))
    if env_scale:
        record["env_scale"] = env_scale

    # MFU-lever sweep (ROADMAP item 2 close-out): chunked RSSM scan at 2/4
    # chunks, scan_unroll=8 and the Pallas LN-GRU, each vs the base graph at
    # XL shapes (where the levers matter; PERF.md §4's table is S/XL)
    mfu_levers = stage(
        "mfu_levers",
        300,
        lambda: measure_mfu_levers(precision, size="XL", batch_size=16, measure_steps=6),
    )
    if mfu_levers:
        record["mfu_levers"] = mfu_levers

    # north-star config (BASELINE.md §C): XL single-chip compute + MFU, at the
    # reference batch (16) and at the MXU-saturating batch (64)
    xl = stage("XL_b16", 240, lambda: measure_compute(precision, size="XL", batch_size=16, measure_steps=40))
    if xl:
        record["dreamer_v3_XL"] = {k: v for k, v in xl.items() if k not in ("flops_per_step", "device_kind")}
    xl_b64 = stage("XL_b64", 240, lambda: measure_compute(precision, size="XL", batch_size=64, measure_steps=25))
    if xl_b64:
        record["dreamer_v3_XL_b64"] = {
            k: v for k, v in xl_b64.items() if k not in ("flops_per_step", "device_kind")
        }
    # XL end-to-end (player+replay+train) at the reference batch — the
    # north-star e2e the round-4 PERF.md projection extrapolated to
    # (VERDICT r4 item 9); fewer iters: each is ~8x an S-size step
    xl_e2e = stage(
        "XL_e2e_b16",
        300,
        lambda: measure_e2e(precision, size="XL", warmup_iters=3, measure_iters=30),
    )
    if xl_e2e:
        record["dreamer_v3_XL_e2e"] = {
            "grad_steps_per_sec_e2e": xl_e2e["grad_steps_per_sec_e2e"],
            "grad_steps_per_sec_e2e_serialized": xl_e2e["grad_steps_per_sec_e2e_serialized"],
        }

    # learn-health block (ISSUE 9): a tiny CPU-subprocess ppo drill whose own
    # journal supplies final loss / mean grad norm / anomaly count —
    # informational, cheap, and isolated from the chip backend
    learn_health = stage("learn_health", 180, measure_learn_health)
    if learn_health:
        record["learn_health"] = learn_health

    # serving block (ISSUE 11): the batched inference tier under an
    # in-process client swarm at three offered-load points — requests/sec,
    # p50/p99 latency and the batch-width amortization the dynamic batcher
    # achieves (PERF.md §4 is the capacity model the buckets come from)
    serving = stage("serving", 120, measure_serving)
    if serving:
        record["serving"] = serving

    # recovery block (ISSUE 13): checkpoint write ms off- vs on-critical-path
    # and measured time-to-recover from one injected kill — the drill runs a
    # CPU subprocess by design, so chip rounds carry the same numbers
    recovery = stage("recovery", 240, measure_recovery)
    if recovery:
        record["recovery"] = recovery

    # decoupled-topology overhead pair (ISSUE 14 / VERDICT item 7): coupled@7
    # vs decoupled@1+7 PPO on the virtual 8-device CPU mesh — subprocesses by
    # design, so chip rounds carry the same serialization canary.  est covers
    # the true worst case: two children, each bounded by its own timeout
    decoupled = stage("decoupled", 500, lambda: measure_decoupled(timeout_s=240.0))
    if decoupled:
        record["decoupled"] = decoupled

    # offline-RL block (ISSUE 15): loader read throughput (prefetch off/on)
    # + the env-free SAC drill's grad-steps/s from its own journal — CPU
    # subprocesses by design, so chip rounds carry the same numbers.  est
    # covers the true worst case: two children, each bounded by its own
    # 420 s timeout (the decoupled-stage lesson)
    offline = stage("offline", 860, measure_offline)
    if offline:
        record["offline"] = offline

    # FSDP-vs-DP pair (ISSUE 17): the sharded-train-state memory win and its
    # step-time cost on the virtual 8-device CPU mesh — a subprocess by
    # design, so chip rounds carry the same canary; XL shapes (where the
    # per-device bytes actually matter), short sequences to keep the CPU
    # child inside its timeout
    fsdp = stage(
        "fsdp",
        500,
        lambda: measure_fsdp(
            precision, size="XL", batch_size=8, sequence_length=8, iters=3, timeout_s=420.0
        ),
    )
    if fsdp:
        record["fsdp"] = fsdp


def main() -> None:
    precision = os.environ.get("BENCH_PRECISION", "bf16-mixed")
    # hard wall-clock budget: the driver must ALWAYS get the JSON line
    # (round 4's rc=124 meant zero recorded numbers — VERDICT r4 weak #1)
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "1500"))
    deadline = time.monotonic() + budget_s
    record = {
        "metric": "dreamer_v3_S_grad_steps_per_sec_e2e",
        "value": None,
        "unit": "grad-steps/s end-to-end (player+env+replay+train, batch 16 x seq 64, ratio 1)",
        "vs_baseline": None,
        "baseline": "reference DV3-S Atari-100K: 25k grad steps / 14 h on RTX-3080 = 0.496/s e2e",
        "precision": precision,
        # memory observability (ISSUE 4): always present.  hbm_peak_bytes is
        # the max per-device peak_bytes_in_use after the menu;
        # host_transfer_count counts the runtime's transfer-guard log lines
        # around the headline e2e stage.  Both null when the backend cannot
        # report them (CPU fallback: memory_stats() is None and the liveness
        # probe skips the guarded stage).
        "hbm_peak_bytes": None,
        "host_transfer_count": None,
        # run-lifecycle observability (ISSUE 8): train share of the pipelined
        # e2e window (set by the e2e stages on both the chip and CPU-fallback
        # paths).  Informational — see measure_e2e; the live Telemetry/goodput
        # gauge is the meaningful production number.
        "goodput": None,
        # learning-dynamics observability (ISSUE 9): final loss / mean grad
        # norm / anomaly count from a tiny CLI drill run's own journal
        # (measure_learn_health).  Informational — null when the drill stage
        # was skipped or failed.
        "learn_health": None,
        # serving tier (ISSUE 11): requests/sec, p50/p99 latency and mean
        # batch width at several offered loads through the real HTTP /act
        # path (measure_serving; the CPU fallback runs the smallest load).
        # Null when the stage was skipped or failed.
        "serving": None,
        # resilience (ISSUE 13): blocking vs async checkpoint write cost,
        # simulated-interval goodput with each, and the supervised
        # injected-kill drill's measured time-to-recover (measure_recovery).
        # Null when the stage was skipped or failed.
        "recovery": None,
        # decoupled topology (ISSUE 14 / VERDICT item 7): coupled-vs-decoupled
        # PPO steady-state iteration pair on the virtual 8-device CPU mesh
        # (measure_decoupled) — the scatter/params-hop overhead ratio.  Null
        # when the stage was skipped or failed.
        "decoupled": None,
        # offline RL (ISSUE 15): dataset read-sps with the prefetch thread
        # off vs on, plus the env-free SAC drill's grad-steps/s and live
        # dataset_read_sps from its own journal (measure_offline).  Null when
        # the stage was skipped or failed.
        "offline": None,
        # FSDP sharding (ISSUE 17): DP-vs-FSDP DV3 step pair on the virtual
        # 8-device mesh — per-device param/opt bytes under the partition rule
        # (params_per_device_shrink ~ the ZeRO-3 win) and the step-time ratio
        # (measure_fsdp).  Null when the stage was skipped or failed.
        "fsdp": None,
        # CPU-fallback regression floor (VERDICT item 5): value vs the pinned
        # conservative CPU floor, with a contention-variance caveat.  Null on
        # chip rounds (the fallback path fills it).
        "vs_cpu_baseline": None,
        # MFU-lever sweep (ROADMAP item 2 close-out): per-variant step_ms for
        # the chunked RSSM scan (rssm_chunks 2/4), scan_unroll=8 and the
        # Pallas LN-GRU vs the base graph (measure_mfu_levers; chip menu runs
        # it at XL shapes, the CPU fallback runs the smallest base-vs-chunks2
        # point).  Null when the stage was skipped or failed.
        "mfu_levers": None,
    }
    emitted = False

    def _emit() -> None:
        nonlocal emitted
        if not emitted:
            emitted = True
            print(json.dumps(record), flush=True)

    def _on_term(signum, frame):  # noqa: ANN001
        # best-effort: if the driver times the bench out (SIGTERM) while a
        # stage is still in Python-level code, land the partial record
        # instead of nothing.  (A hang inside a blocking device call cannot
        # be preempted — the budget gates above keep stages short enough
        # that this is the rare case, not the common one.)
        record["terminated"] = f"signal {signum} mid-run — partial results"
        _emit()
        raise SystemExit(124)

    import signal

    signal.signal(signal.SIGTERM, _on_term)
    try:
        device_fallback = _ensure_responsive_device()
        if device_fallback:
            record["device_fallback"] = device_fallback
            _run_cpu_fallback(record, precision)
        else:
            _run_chip_menu(record, precision, deadline)
    except Exception as err:  # noqa: BLE001 — the JSON line must land regardless
        record["error"] = repr(err)
    finally:
        try:
            # peak HBM across the whole menu (device allocator high-water
            # mark); stays null on backends without memory_stats (CPU)
            from sheeprl_tpu.diagnostics.memory import device_memory_stats

            stats = device_memory_stats()
            if stats:
                record["hbm_peak_bytes"] = max(
                    int(s.get("peak_bytes_in_use", 0) or 0) for s in stats
                ) or None
        except Exception:  # noqa: BLE001
            pass
        _emit()
    if record.get("value") is None:
        # the JSON landed, but without the headline measurement (top-level
        # failure, every stage failing inside the stage() wrapper, or the
        # budget skipping the headline stage): fail at the process level too
        # so a return-code-gating driver doesn't record success
        raise SystemExit(1)


if __name__ == "__main__":
    main()
