"""Repo-root shim so ``python sheeprl.py ...`` works like the reference's
root-level launcher (reference /root/reference/sheeprl.py)."""

import os
import sys

# The host BLAS/OpenMP pools size themselves when numpy loads, which happens
# as soon as the package imports — so a `num_threads=N` override must be
# applied to the environment *before* any import.
for _arg in sys.argv[1:]:
    if _arg.startswith("num_threads="):
        _n = _arg.split("=", 1)[1]
        if _n.isdigit() and int(_n) > 0:
            for _var in ("OMP_NUM_THREADS", "OPENBLAS_NUM_THREADS", "MKL_NUM_THREADS"):
                os.environ.setdefault(_var, _n)

from sheeprl_tpu.cli import run  # noqa: E402

if __name__ == "__main__":
    run()
