"""Repo-root shim so ``python sheeprl.py ...`` works like the reference's
root-level launcher (reference /root/reference/sheeprl.py)."""

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    run()
