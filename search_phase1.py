#!/usr/bin/env python3
"""Repo-root shim for the hyperparameter search harness (the fork keeps
`search_phase1.py` at the repo root — /root/reference/search_phase1.py).
Implementation: sheeprl_tpu/tools/search.py."""

from sheeprl_tpu.tools.search import main

if __name__ == "__main__":
    main()
