#!/usr/bin/env python3
"""Repo-root shim for reward-log recovery (the fork keeps
`recover_reward_logs.py` at the repo root — /root/reference/recover_reward_logs.py).
Implementation: sheeprl_tpu/tools/recover_rewards.py."""

from sheeprl_tpu.tools.recover_rewards import main

if __name__ == "__main__":
    main()
